"""C API shim test: build the native library and drive the LGBM_* surface
through ctypes (reference: include/LightGBM/c_api.h round-trip tests)."""

import ctypes
import os
import subprocess
import sysconfig

import numpy as np
import pytest

import lightgbm_tpu as lgb

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "src", "capi", "lightgbm_tpu_c_api.cpp")
_SO = os.path.join(_REPO, "src", "capi", "_lightgbm_tpu_c_api.so")


def _build():
    if os.path.exists(_SO) and os.path.getmtime(_SO) > os.path.getmtime(_SRC):
        return _SO
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = f"python{sysconfig.get_config_var('py_version_short')}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{inc}", _SRC, "-o", _SO, f"-L{libdir}", f"-l{pyver}",
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _SO


def test_c_api_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4).astype(np.float64)
    y = ((X @ rng.randn(4)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1}, train_set=ds)
    for _ in range(3):
        bst.update()
    model_path = str(tmp_path / "m.txt")
    bst.save_model(model_path)
    expect = bst.predict(X)

    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    handle = ctypes.c_void_p()
    out_iters = ctypes.c_int()
    rc = lib.LGBM_BoosterCreateFromModelfile(
        model_path.encode(), ctypes.byref(out_iters), ctypes.byref(handle)
    )
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_iters.value == 3

    ncls = ctypes.c_int()
    assert lib.LGBM_BoosterGetNumClasses(handle, ctypes.byref(ncls)) == 0
    assert ncls.value == 1

    out = np.zeros(len(X), np.float64)
    out_len = ctypes.c_int64()
    rc = lib.LGBM_BoosterPredictForMat(
        handle,
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
        ctypes.c_int32(1), ctypes.c_int32(0),
        ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_len.value == len(X)
    assert np.abs(out - expect).max() < 1e-10

    # save through the C surface and reload
    out_path = str(tmp_path / "m2.txt")
    assert lib.LGBM_BoosterSaveModel(handle, 0, -1, 0, out_path.encode()) == 0
    bst2 = lgb.Booster(model_file=out_path)
    assert np.abs(bst2.predict(X) - expect).max() < 1e-12

    # error path: bad file reports through LGBM_GetLastError
    h2 = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreateFromModelfile(
        b"/nonexistent/model.txt", ctypes.byref(out_iters), ctypes.byref(h2)
    )
    assert rc == -1
    assert lib.LGBM_GetLastError()

    assert lib.LGBM_BoosterFree(handle) == 0
