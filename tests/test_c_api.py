"""C API shim test: build the native library and drive the LGBM_* surface
through ctypes (reference: include/LightGBM/c_api.h round-trip tests)."""

import ctypes
import os
import subprocess
import sysconfig

import numpy as np
import pytest

import lightgbm_tpu as lgb

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "src", "capi", "lightgbm_tpu_c_api.cpp")
_SO = os.path.join(_REPO, "src", "capi", "_lightgbm_tpu_c_api.so")

pytestmark = pytest.mark.slow


def _build():
    if os.path.exists(_SO) and os.path.getmtime(_SO) > os.path.getmtime(_SRC):
        return _SO
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = f"python{sysconfig.get_config_var('py_version_short')}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{inc}", _SRC, "-o", _SO, f"-L{libdir}", f"-l{pyver}",
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _SO


def test_c_api_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4).astype(np.float64)
    y = ((X @ rng.randn(4)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbosity": -1}, train_set=ds)
    for _ in range(3):
        bst.update()
    model_path = str(tmp_path / "m.txt")
    bst.save_model(model_path)
    expect = bst.predict(X)

    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    handle = ctypes.c_void_p()
    out_iters = ctypes.c_int()
    rc = lib.LGBM_BoosterCreateFromModelfile(
        model_path.encode(), ctypes.byref(out_iters), ctypes.byref(handle)
    )
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_iters.value == 3

    ncls = ctypes.c_int()
    assert lib.LGBM_BoosterGetNumClasses(handle, ctypes.byref(ncls)) == 0
    assert ncls.value == 1

    out = np.zeros(len(X), np.float64)
    out_len = ctypes.c_int64()
    rc = lib.LGBM_BoosterPredictForMat(
        handle,
        X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
        ctypes.c_int(1), ctypes.c_int(0),
        ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_len.value == len(X)
    assert np.abs(out - expect).max() < 1e-10

    # save through the C surface and reload
    out_path = str(tmp_path / "m2.txt")
    assert lib.LGBM_BoosterSaveModel(handle, 0, -1, 0, out_path.encode()) == 0
    bst2 = lgb.Booster(model_file=out_path)
    assert np.abs(bst2.predict(X) - expect).max() < 1e-12

    # error path: bad file reports through LGBM_GetLastError
    h2 = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreateFromModelfile(
        b"/nonexistent/model.txt", ctypes.byref(out_iters), ctypes.byref(h2)
    )
    assert rc == -1
    assert lib.LGBM_GetLastError()

    assert lib.LGBM_BoosterFree(handle) == 0


def test_c_api_training_workflow():
    """Full train-from-C workflow: dataset from mat + set label + booster
    create + update + eval + save-to-string (reference: the c_api_test
    pattern tests/c_api_test/test_.py)."""
    rng = np.random.RandomState(1)
    X = np.ascontiguousarray(rng.randn(400, 5))
    y = np.ascontiguousarray(((X[:, 0] + X[:, 1]) > 0).astype(np.float32))

    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    ds = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),  # FLOAT64
        ctypes.c_int32(400), ctypes.c_int32(5), ctypes.c_int(1),
        b"max_bin=63 min_data_in_leaf=5", None, ctypes.byref(ds))
    assert rc == 0, lib.LGBM_GetLastError()

    rc = lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(400), ctypes.c_int(0))  # FLOAT32
    assert rc == 0, lib.LGBM_GetLastError()

    nd, nf = ctypes.c_int32(), ctypes.c_int32()
    assert lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)) == 0
    assert lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(nf)) == 0
    assert (nd.value, nf.value) == (400, 5)

    bst = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=-1 metric=binary_logloss",
        ctypes.byref(bst))
    assert rc == 0, lib.LGBM_GetLastError()

    fin = ctypes.c_int()
    for _ in range(5):
        assert lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0
    it = ctypes.c_int()
    assert lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)) == 0
    assert it.value == 5

    assert lib.LGBM_BoosterRollbackOneIter(bst) == 0
    assert lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)) == 0
    assert it.value == 4

    ntot = ctypes.c_int()
    assert lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(ntot)) == 0
    assert ntot.value == 4
    nfeat = ctypes.c_int()
    assert lib.LGBM_BoosterGetNumFeature(bst, ctypes.byref(nfeat)) == 0
    assert nfeat.value == 5

    # eval on the training set
    cnt = ctypes.c_int()
    assert lib.LGBM_BoosterGetEvalCounts(bst, ctypes.byref(cnt)) == 0
    assert cnt.value >= 1
    vals = np.zeros(cnt.value, np.float64)
    out_len = ctypes.c_int()
    rc = lib.LGBM_BoosterGetEval(
        bst, 0, ctypes.byref(out_len),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_len.value == cnt.value
    assert 0 < vals[0] < 1.0  # logloss of a learning model

    # model to string: size call then fill call (reference contract)
    need = ctypes.c_int64()
    rc = lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, ctypes.c_int64(0), ctypes.byref(need), None)
    assert rc == 0, lib.LGBM_GetLastError()
    buf = ctypes.create_string_buffer(need.value)
    rc = lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, need, ctypes.byref(need), buf)
    assert rc == 0
    model_str = buf.value.decode()
    assert model_str.startswith("tree")
    bst_py = lgb.Booster(model_str=model_str)
    assert bst_py.num_trees() == 4

    # feature importance
    imp = np.zeros(5, np.float64)
    rc = lib.LGBM_BoosterFeatureImportance(
        bst, 0, 0, imp.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0
    assert imp.sum() > 0

    # reset parameter
    assert lib.LGBM_BoosterResetParameter(bst, b"learning_rate=0.25") == 0

    # custom objective update
    n = 400
    pred = bst_py.predict(X, raw_score=True)
    p = 1.0 / (1.0 + np.exp(-pred))
    grad = np.ascontiguousarray((p - y).astype(np.float32))
    hess = np.ascontiguousarray((p * (1 - p)).astype(np.float32))
    rc = lib.LGBM_BoosterUpdateOneIterCustom(
        bst, grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(fin))
    assert rc == 0, lib.LGBM_GetLastError()
    assert lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)) == 0
    assert it.value == 5

    assert lib.LGBM_BoosterFree(bst) == 0
    assert lib.LGBM_DatasetFree(ds) == 0


def test_c_api_push_rows_streaming():
    """Streamed construction == bulk construction (reference:
    tests/cpp_tests/test_stream.cpp pattern)."""
    rng = np.random.RandomState(3)
    X = np.ascontiguousarray(rng.randn(300, 4))
    y = np.ascontiguousarray((X[:, 0] > 0).astype(np.float32))
    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    ref = ctypes.c_void_p()
    assert lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 1, b"max_bin=31",
        None, ctypes.byref(ref)) == 0

    ds = ctypes.c_void_p()
    assert lib.LGBM_DatasetCreateByReference(
        ref, ctypes.c_int64(300), ctypes.byref(ds)) == 0, lib.LGBM_GetLastError()
    # push in 3 blocks of 100
    for s in (0, 100, 200):
        blk = np.ascontiguousarray(X[s:s + 100])
        assert lib.LGBM_DatasetPushRows(
            ds, blk.ctypes.data_as(ctypes.c_void_p), 1, 100, 4,
            ctypes.c_int32(s)) == 0, lib.LGBM_GetLastError()
    assert lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 300, 0) == 0

    nd = ctypes.c_int32()
    assert lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)) == 0
    assert nd.value == 300

    bst = ctypes.c_void_p()
    assert lib.LGBM_BoosterCreate(
        ds, b"objective=binary verbosity=-1 num_leaves=7",
        ctypes.byref(bst)) == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int()
    for _ in range(3):
        assert lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0

    # model equals training on the bulk dataset with the same params
    need = ctypes.c_int64()
    assert lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, ctypes.c_int64(0), ctypes.byref(need), None) == 0
    buf = ctypes.create_string_buffer(need.value)
    assert lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, need, ctypes.byref(need), buf) == 0
    streamed = buf.value.decode()

    d_ref = lgb.Dataset(X, label=y.astype(np.float64), params={"max_bin": 31})
    bst_py = lgb.train({"objective": "binary", "verbosity": -1,
                        "num_leaves": 7, "max_bin": 31},
                       lgb.Dataset(X, label=y.astype(np.float64),
                                   reference=d_ref, params={"max_bin": 31}),
                       num_boost_round=3)
    np.testing.assert_allclose(
        lgb.Booster(model_str=streamed).predict(X), bst_py.predict(X),
        rtol=1e-6, atol=1e-8)
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)
    lib.LGBM_DatasetFree(ref)


def test_c_api_dump_model_json():
    rng = np.random.RandomState(2)
    X = np.ascontiguousarray(rng.randn(200, 3))
    y = np.ascontiguousarray((X[:, 0] > 0).astype(np.float32))
    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    ds = ctypes.c_void_p()
    assert lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, 200, 3, 1, b"",
        None, ctypes.byref(ds)) == 0
    assert lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 200, 0) == 0
    bst = ctypes.c_void_p()
    assert lib.LGBM_BoosterCreate(
        ds, b"objective=binary verbosity=-1", ctypes.byref(bst)) == 0
    fin = ctypes.c_int()
    assert lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0

    need = ctypes.c_int64()
    assert lib.LGBM_BoosterDumpModel(
        bst, 0, -1, 0, ctypes.c_int64(0), ctypes.byref(need), None) == 0
    buf = ctypes.create_string_buffer(need.value)
    assert lib.LGBM_BoosterDumpModel(
        bst, 0, -1, 0, need, ctypes.byref(need), buf) == 0
    import json

    model = json.loads(buf.value.decode())
    assert model["num_class"] == 1 and len(model["tree_info"]) == 1
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_c_api_csr_and_single_row_fast(tmp_path):
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(1)
    Xd = rng.randn(600, 6)
    Xd[rng.rand(600, 6) < 0.6] = 0.0
    X = sp.csr_matrix(Xd)
    y = ((Xd @ rng.randn(6)) > 0).astype(np.float64)

    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    indptr = np.asarray(X.indptr, np.int32)
    indices = np.asarray(X.indices, np.int32)
    data = np.asarray(X.data, np.float64)

    # dataset from CSR -> train -> predictions must match the dense path
    dsh = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromCSR(
        indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(6), b"max_bin=63", None, ctypes.byref(dsh))
    assert rc == 0, lib.LGBM_GetLastError()
    yv = y.astype(np.float32)
    rc = lib.LGBM_DatasetSetField(dsh, b"label",
                                  yv.ctypes.data_as(ctypes.c_void_p),
                                  ctypes.c_int(len(yv)), 0)
    assert rc == 0, lib.LGBM_GetLastError()
    bh = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreate(dsh, b"objective=binary num_leaves=7 verbosity=-1",
                                ctypes.byref(bh))
    assert rc == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int()
    for _ in range(5):
        assert lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)) == 0

    # reference model trained through the Python API on the dense matrix
    ds_py = lgb.Dataset(Xd, label=y, params={"max_bin": 63})
    bst_py = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                                 "verbosity": -1}, train_set=ds_py)
    for _ in range(5):
        bst_py.update()
    expect = bst_py.predict(Xd)

    # CSR batch predict
    out = np.zeros(600, np.float64)
    out_len = ctypes.c_int64()
    rc = lib.LGBM_BoosterPredictForCSR(
        bh, indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(6), 0, 0, -1, b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_len.value == 600
    np.testing.assert_allclose(out, expect, rtol=1e-6, atol=1e-9)

    # single-row plain + Fast must match batch predictions
    one = np.zeros(1, np.float64)
    row = np.ascontiguousarray(Xd[17], np.float64)
    rc = lib.LGBM_BoosterPredictForMatSingleRow(
        bh, row.ctypes.data_as(ctypes.c_void_p), 1, 6, 1, 0, 0, -1, b"",
        ctypes.byref(out_len), one.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    assert one[0] == pytest.approx(expect[17], rel=1e-6)

    fch = ctypes.c_void_p()
    rc = lib.LGBM_BoosterPredictForMatSingleRowFastInit(
        bh, 0, 0, -1, 1, 6, b"", ctypes.byref(fch))
    assert rc == 0, lib.LGBM_GetLastError()
    for i in (3, 99, 400):
        row = np.ascontiguousarray(Xd[i], np.float64)
        rc = lib.LGBM_BoosterPredictForMatSingleRowFast(
            fch, row.ctypes.data_as(ctypes.c_void_p), ctypes.byref(out_len),
            one.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        assert rc == 0, lib.LGBM_GetLastError()
        assert one[0] == pytest.approx(expect[i], rel=1e-6)
    assert lib.LGBM_FastConfigFree(fch) == 0
    assert lib.LGBM_BoosterFree(bh) == 0
    assert lib.LGBM_DatasetFree(dsh) == 0
