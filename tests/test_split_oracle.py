"""P0 exit gate: histogram + split search vs. hand-computed oracles
(SURVEY.md §10.2 P0; reference semantics from feature_histogram.hpp)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import histogram_onehot_matmul, histogram_scatter
from lightgbm_tpu.ops.split import SplitParams, find_best_split
from lightgbm_tpu.ops.treegrow import grow_tree


def _oracle_hist(bins, grad, hess, mask, num_bins):
    n, f = bins.shape
    out = np.zeros((f, num_bins, 3))
    for i in range(n):
        if not mask[i]:
            continue
        for j in range(f):
            b = bins[i, j]
            out[j, b, 0] += grad[i]
            out[j, b, 1] += hess[i]
            out[j, b, 2] += 1
    return out


@pytest.mark.parametrize("fn", [histogram_scatter, histogram_onehot_matmul])
def test_histogram_matches_oracle(fn):
    rng = np.random.RandomState(0)
    n, f, b = 500, 4, 16
    bins = rng.randint(0, b, size=(n, f)).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = (rng.rand(n) < 0.7).astype(np.float32)
    hist = np.asarray(fn(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), b))
    oracle = _oracle_hist(bins, grad, hess, mask, b)
    # package layout is channel-first (3, F, B); oracle builds (F, B, 3)
    np.testing.assert_allclose(hist, oracle.transpose(2, 0, 1), rtol=1e-4, atol=1e-4)


def _oracle_best_split(hist, nbins, miss_bin, params: SplitParams):
    """Brute-force best split over (feature, threshold, missing-dir)."""
    f, b, _ = hist.shape

    def gain(G, H):
        tg = np.sign(G) * max(abs(G) - params.lambda_l1, 0.0)
        return tg * tg / (H + params.lambda_l2 + 1e-15)

    tot = hist[0].sum(axis=0)
    best = (-1e30, -1, -1, False)
    for j in range(f):
        mb = miss_bin[j]
        nb = nbins[j]
        miss = hist[j, mb] if mb >= 0 else np.zeros(3)
        last_nm = nb - 2 if mb >= 0 else nb - 1
        for t in range(last_nm):
            left = hist[j, : t + 1].sum(axis=0)
            if mb >= 0 and mb <= t:
                left = left - hist[j, mb]
            for missing_left in (False, True):
                l = left + (miss if missing_left else 0)
                r = tot - l
                if l[2] < params.min_data_in_leaf or r[2] < params.min_data_in_leaf:
                    continue
                if l[1] < params.min_sum_hessian_in_leaf or r[1] < params.min_sum_hessian_in_leaf:
                    continue
                g = gain(l[0], l[1]) + gain(r[0], r[1]) - gain(tot[0], tot[1])
                if g > params.min_gain_to_split and g > best[0] + 1e-9:
                    best = (g, j, t, missing_left)
    return best


def test_split_matches_oracle():
    rng = np.random.RandomState(1)
    f, b = 5, 12
    hist = rng.randn(f, b, 3).astype(np.float32)
    hist[..., 1] = np.abs(hist[..., 1])  # hessians positive
    hist[..., 2] = rng.randint(1, 50, size=(f, b))
    nbins = np.full(f, b, np.int32)
    nbins[1] = 8  # ragged bin counts
    miss_bin = np.full(f, -1, np.int32)
    miss_bin[2] = b - 1
    # zero out invalid bins for the ragged feature
    hist[1, 8:] = 0.0
    params = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
    tot = hist[0].sum(axis=0)
    # make totals consistent across features (hist of the same rows)
    for j in range(1, f):
        scale = tot / np.where(hist[j].sum(axis=0) == 0, 1, hist[j].sum(axis=0))
        hist[j] *= scale[None, :]

    s = find_best_split(
        jnp.asarray(hist.transpose(2, 0, 1)),  # channel-first (3, F, B)
        jnp.asarray(tot[0]),
        jnp.asarray(tot[1]),
        jnp.asarray(tot[2]),
        jnp.asarray(nbins),
        jnp.asarray(miss_bin),
        params,
    )
    og, oj, ot, oml = _oracle_best_split(hist, nbins, miss_bin, params)
    assert abs(float(s.gain) - og) < 1e-3 * max(1.0, abs(og))
    assert int(s.feature) == oj
    assert int(s.threshold_bin) == ot


def test_grow_tree_single_split_oracle():
    """One split on a tiny crafted dataset matches hand computation."""
    # feature 0: clean separator; feature 1: noise
    bins = np.array([[0, 1], [0, 0], [0, 1], [1, 0], [1, 1], [1, 0]], np.int32)
    grad = np.array([1.0, 1.0, 1.0, -1.0, -1.0, -1.0], np.float32)
    hess = np.ones(6, np.float32)
    params = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
    tree, leaf_id = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(6, bool), jnp.ones(6, jnp.float32), jnp.ones(2, bool),
        jnp.asarray([2, 2], jnp.int32), jnp.asarray([-1, -1], jnp.int32),
        num_leaves=2, num_bins=2, params=params,
    )
    assert int(tree.num_leaves) == 2
    assert int(tree.split_feature[0]) == 0
    assert int(tree.threshold_bin[0]) == 0
    # leaf values: -G/H = -3/3 = -1 (left), +1 -> -(-3)/3 = 1 (right)
    lv = np.asarray(tree.leaf_value)
    np.testing.assert_allclose(sorted(lv[:2]), [-1.0, 1.0], atol=1e-6)
    # gain oracle: G_L=3,H_L=3 ; G_R=-3,H_R=3 ; parent G=0 H=6
    # gain = 9/3 + 9/3 - 0 = 6
    np.testing.assert_allclose(float(tree.split_gain[0]), 6.0, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(leaf_id), [0, 0, 0, 1, 1, 1])


def test_grow_tree_respects_min_data():
    rng = np.random.RandomState(3)
    n = 100
    bins = rng.randint(0, 10, size=(n, 3)).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    params = SplitParams(min_data_in_leaf=20, min_sum_hessian_in_leaf=0.0)
    tree, leaf_id = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, bool), jnp.ones(n, jnp.float32), jnp.ones(3, bool),
        jnp.asarray([10, 10, 10], jnp.int32), jnp.asarray([-1, -1, -1], jnp.int32),
        num_leaves=16, num_bins=10, params=params,
    )
    counts = np.asarray(tree.leaf_count)[: int(tree.num_leaves)]
    assert (counts >= 20).all()


def test_grow_tree_depth_cap():
    rng = np.random.RandomState(4)
    n = 512
    bins = rng.randint(0, 16, size=(n, 4)).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    params = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
    tree, _ = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, bool), jnp.ones(n, jnp.float32), jnp.ones(4, bool),
        jnp.asarray([16] * 4, jnp.int32), jnp.asarray([-1] * 4, jnp.int32),
        num_leaves=31, num_bins=16, max_depth=3, params=params,
    )
    depths = np.asarray(tree.leaf_depth)[: int(tree.num_leaves)]
    assert depths.max() <= 3
    assert int(tree.num_leaves) <= 8
