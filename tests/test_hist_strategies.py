"""Histogram strategy equivalence (reference analogue: col-wise vs
row-wise hist paths must agree — TrainingShareStates)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import (
    histogram_onehot_multi,
    histogram_scatter,
)


@pytest.mark.parametrize("B", [16, 64])
def test_onehot_multi_matches_scatter_per_leaf(B):
    n, F, L = 5000, 6, 4
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)).astype(np.int16))
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(rng.rand(n).astype(np.float32))
    mask = jnp.asarray(rng.rand(n) < 0.8)
    lid = jnp.asarray(rng.randint(0, L, size=(n,)).astype(np.int32))

    out = histogram_onehot_multi(bins, grad, hess, mask, lid, 0, L, B)
    assert out.shape == (L, 3, F, B)
    for leaf in range(L):
        m = (mask & (lid == leaf)).astype(jnp.float32)
        ref = histogram_scatter(bins, grad, hess, m, B)
        scale = np.abs(np.asarray(ref)).max() + 1
        rel = np.max(np.abs(np.asarray(out[leaf]) - np.asarray(ref))) / scale
        assert rel < 2e-4, (leaf, rel)


def test_onehot_multi_leaf_base_offset():
    n, F, B, L = 2000, 3, 32, 2
    rng = np.random.RandomState(1)
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)).astype(np.int16))
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(rng.rand(n).astype(np.float32))
    mask = jnp.ones((n,), bool)
    lid = jnp.asarray(rng.randint(5, 5 + L, size=(n,)).astype(np.int32))
    out = histogram_onehot_multi(bins, grad, hess, mask, lid, 5, L, B)
    m0 = (lid == 5).astype(jnp.float32)
    ref0 = histogram_scatter(bins, grad, hess, m0, B)
    rel = np.max(np.abs(np.asarray(out[0]) - np.asarray(ref0))) / (
        np.abs(np.asarray(ref0)).max() + 1)
    assert rel < 2e-4


def test_pallas_hist_paths_trace_on_cpu():
    """jax.eval_shape traces the pallas histogram builders without TPU
    compilation — catches Python-level breakage (e.g. a bad cost_estimate)
    in the narrow AND the wide (per-128-feature chunked) paths, which only
    real-TPU runs would otherwise reach."""
    import jax

    from lightgbm_tpu.ops.hist_pallas import (histogram_pallas,
                                              histogram_pallas_multi)

    for f in (28, 300):  # narrow; wide enough to take the chunked branch
        n = 256
        bins = jnp.zeros((n, f), jnp.int16)
        g = h = m = jnp.zeros((n,), jnp.float32)
        out = jax.eval_shape(
            lambda b, g_, h_, m_: histogram_pallas(b, g_, h_, m_, 63),
            bins, g, h, m)
        assert out.shape == (3, f, 63)
        lid = jnp.zeros((n,), jnp.int32)
        out = jax.eval_shape(
            lambda b, g_, h_, m_, l_: histogram_pallas_multi(
                b, g_, h_, m_, l_, 0, 4, 63),
            bins, g, h, m, lid)
        assert out.shape == (4, 3, f, 63)


def test_quantized_onehot_multi_exact_int32():
    """The XLA int8 one-hot quantized histogram (narrow-bin strategy) must
    produce EXACT integer sums, matching a numpy reference."""
    from lightgbm_tpu.ops.histogram import histogram_onehot_multi_quantized

    rng = np.random.RandomState(0)
    n, f, B, tile = 5000, 6, 63, 4
    bins = rng.randint(0, B, (n, f)).astype(np.int16)
    gq = rng.randint(-127, 128, n).astype(np.int8)
    hq = rng.randint(0, 128, n).astype(np.int8)
    mask = rng.rand(n) < 0.8
    leaf = rng.randint(0, tile, n).astype(np.int32)
    out = np.asarray(histogram_onehot_multi_quantized(
        jnp.asarray(bins), jnp.asarray(gq), jnp.asarray(hq),
        jnp.asarray(mask), jnp.asarray(leaf), 0, tile, B))
    assert out.dtype == np.int32
    ref = np.zeros((tile, 3, f, B), np.int64)
    for l in range(tile):
        m = mask & (leaf == l)
        for c, v in enumerate((gq.astype(np.int64), hq.astype(np.int64),
                               np.ones(n, np.int64))):
            for j in range(f):
                ref[l, c, j, :] = np.bincount(
                    bins[m, j], weights=v[m], minlength=B)[:B]
    np.testing.assert_array_equal(out.astype(np.int64), ref)


def test_fast_grower_tpu_branches_trace_on_cpu():
    """eval_shape the ROUND-BATCHED grower with use_pallas=True through the
    strategy-selection branches the suite otherwise never reaches off-TPU:
    float narrow (XLA), float wide (Pallas), quantized narrow (XLA int8),
    quantized wide (Pallas int8)."""
    import jax

    from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast

    n, f = 512, 5
    for num_bins, quant in ((63, 0), (255, 0), (63, 4), (255, 4)):
        bins = jnp.zeros((n, f), jnp.int16)
        g = h = sw = jnp.zeros((n,), jnp.float32)
        rm = jnp.ones((n,), bool)
        fm = jnp.ones((f,), bool)
        nbpf = jnp.full((f,), num_bins, jnp.int32)
        mbpf = jnp.full((f,), -1, jnp.int32)

        def run(bins, g, h, rm, sw, fm, nbpf, mbpf, _nb=num_bins, _q=quant):
            return grow_tree_fast(
                bins, g, h, rm, sw, fm, nbpf, mbpf,
                None, None, None, None,
                jax.random.PRNGKey(0) if _q else None,
                None, None, None, None, None, None, None, None, None,
                num_leaves=7, num_bins=_nb, params=__import__(
                    "lightgbm_tpu.ops.split", fromlist=["SplitParams"]
                ).SplitParams(),
                use_pallas=True, quantize_bins=_q,
            )
        arrays, leaf = jax.eval_shape(run, bins, g, h, rm, sw, fm, nbpf, mbpf)
        assert leaf.shape == (n,)
