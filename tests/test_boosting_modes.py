"""Regression tests for the init-score handling family of bugs plus
DART/RF/GOSS boosting modes (reference behaviors from gbdt.cpp, dart.hpp,
rf.hpp, goss.hpp)."""

import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def shifted_regression():
    """Regression data with a large nonzero mean — catches any path that
    double-counts or drops the boost_from_average init score."""
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 8)
    y = 100.0 + X @ rng.randn(8) + 0.1 * rng.randn(2000)
    return X, y

pytestmark = pytest.mark.slow


def test_valid_scores_not_double_counting_init(shifted_regression):
    X, y = shifted_regression
    train = lgb.Dataset(X[:1500], label=y[:1500])
    valid = lgb.Dataset(X[1500:], label=y[1500:], reference=train)
    rec = {}
    bst = lgb.train(
        {"objective": "regression", "metric": ["l2"], "verbosity": -1},
        train, num_boost_round=5, valid_sets=[valid],
        callbacks=[lgb.record_evaluation(rec)],
    )
    # internal valid-set margin must equal raw predict on the same rows
    internal = np.asarray(bst._gbdt._valid_scores[0])
    raw = bst.predict(X[1500:], raw_score=True)
    np.testing.assert_allclose(internal, raw, rtol=1e-4, atol=1e-3)
    # and the recorded l2 must be sane (not ~100^2 biased)
    assert rec["valid_0"]["l2"][-1] < 50.0


def test_init_model_continuation(shifted_regression):
    X, y = shifted_regression
    params = {"objective": "regression", "verbosity": -1}
    d1 = lgb.Dataset(X, label=y)
    bst1 = lgb.train(params, d1, num_boost_round=5)
    d2 = lgb.Dataset(X, label=y)
    bst2 = lgb.train(params, d2, num_boost_round=5, init_model=bst1)
    assert bst2.num_trees() == 10
    pred = bst2.predict(X)
    mse10 = np.mean((pred - y) ** 2)
    mse5 = np.mean((bst1.predict(X) - y) ** 2)
    assert mse10 < mse5  # continued training improves
    # margins consistent: internal score == predict
    np.testing.assert_allclose(
        np.asarray(bst2._gbdt._score), pred, rtol=1e-4, atol=1e-3
    )


def test_dart_with_shifted_labels(shifted_regression):
    X, y = shifted_regression
    bst = lgb.train(
        {"objective": "regression", "boosting": "dart", "drop_rate": 0.5,
         "verbosity": -1, "drop_seed": 7},
        lgb.Dataset(X, label=y), num_boost_round=15,
    )
    pred = bst.predict(X)
    # DART rescaling must never corrupt the ~100 baseline
    assert abs(pred.mean() - y.mean()) < 5.0
    assert np.mean((pred - y) ** 2) < np.var(y)
    # save/load parity
    re = lgb.Booster.model_from_string(bst.model_to_string())
    np.testing.assert_allclose(pred, re.predict(X), rtol=1e-5, atol=1e-5)


def test_rf_mode(shifted_regression):
    X, y = shifted_regression
    rec = {}
    train = lgb.Dataset(X[:1500], label=y[:1500])
    valid = lgb.Dataset(X[1500:], label=y[1500:], reference=train)
    bst = lgb.train(
        {"objective": "regression", "boosting": "rf", "bagging_fraction": 0.7,
         "bagging_freq": 1, "verbosity": -1, "metric": ["l2"]},
        train, num_boost_round=20, valid_sets=[valid],
        callbacks=[lgb.record_evaluation(rec)],
    )
    pred = bst.predict(X[1500:])
    mse = np.mean((pred - y[1500:]) ** 2)
    assert mse < np.var(y)  # beats predicting the mean... loosely
    # eval-time metric must match predict-time metric (averaged margins)
    assert abs(rec["valid_0"]["l2"][-1] - mse) < 0.2 * max(mse, 1.0)
    # save/load roundtrip with average_output
    re = lgb.Booster.model_from_string(bst.model_to_string())
    assert re._gbdt.average_output
    np.testing.assert_allclose(pred, re.predict(X[1500:]), rtol=1e-4, atol=1e-3)


def test_goss_sampling():
    rng = np.random.RandomState(1)
    X = rng.randn(3000, 10)
    y = ((X @ rng.randn(10)) > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "data_sample_strategy": "goss",
         "top_rate": 0.2, "other_rate": 0.2, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=25,
    )
    assert roc_auc_score(y, bst.predict(X)) > 0.9


def test_is_unbalance_weights_positives():
    rng = np.random.RandomState(2)
    n = 4000
    X = rng.randn(n, 5)
    y = ((X[:, 0] + rng.randn(n) * 2.0) > 1.8).astype(float)  # ~5% positives
    p_plain = lgb.train(
        {"objective": "binary", "verbosity": -1, "boost_from_average": False},
        lgb.Dataset(X, label=y), 10).predict(X)
    p_unbal = lgb.train(
        {"objective": "binary", "is_unbalance": True, "verbosity": -1,
         "boost_from_average": False},
        lgb.Dataset(X, label=y), 10).predict(X)
    # unbalanced weighting must raise predicted probabilities for positives
    assert p_unbal[y > 0].mean() > p_plain[y > 0].mean() + 0.05


def test_categorical_feature_does_not_crash():
    rng = np.random.RandomState(3)
    X = rng.randn(500, 3)
    X[:, 0] = rng.randint(0, 8, 500)  # categorical codes
    y = (X[:, 1] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "verbosity": -1},
        lgb.Dataset(X, label=y, categorical_feature=[0]), 5,
    )
    p = bst.predict(X)
    assert np.isfinite(p).all()


def test_missing_type_none_nan_prediction_consistency():
    """Rows with NaN at predict time on a feature that had no NaN in
    training must follow the reference's NaN->0.0 convention on the device
    path, matching the host Tree.predict."""
    rng = np.random.RandomState(4)
    X = rng.randn(2000, 4) + 5.0  # all positive-ish, no NaN
    y = (X[:, 0] > 5.0).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), 10)
    X_test = X[:20].copy()
    X_test[:, 0] = np.nan
    dev = bst.predict(X_test, raw_score=True)
    host = sum(t.predict(X_test) for t in bst._gbdt._trees_for_export(0, -1))
    np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-4)


def test_cv_basic():
    rng = np.random.RandomState(5)
    X = rng.randn(600, 6)
    y = ((X @ rng.randn(6)) > 0).astype(float)
    res = lgb.cv({"objective": "binary", "metric": ["auc"], "verbosity": -1},
                 lgb.Dataset(X, label=y), num_boost_round=5, nfold=3)
    assert len(res["valid auc-mean"]) == 5
    assert res["valid auc-mean"][-1] > 0.7


def test_cv_ranking_groups():
    rng = np.random.RandomState(6)
    n_q, per_q = 40, 10
    X = rng.randn(n_q * per_q, 5)
    y = rng.randint(0, 3, n_q * per_q).astype(float)
    g = np.full(n_q, per_q)
    res = lgb.cv({"objective": "lambdarank", "metric": ["ndcg"], "eval_at": [3],
                  "verbosity": -1, "min_data_in_leaf": 5},
                 lgb.Dataset(X, label=y, group=g), num_boost_round=3, nfold=2,
                 stratified=False)
    assert len(res["valid ndcg@3-mean"]) == 3
