"""External-oracle structure parity vs sklearn HistGradientBoosting.

SURVEY.md §5.3 item 1 / VERDICT round-1 item 10: sklearn's HistGBM is the
same histogram + leaf-wise (best-first) algorithm family as the reference;
with binning made trivial (integer features with few distinct values, so
both binners give one bin per value), zero regularization and matched
stopping parameters, one boosting iteration must produce the SAME tree:
same leaf count, same partition of the training rows, same leaf values —
an oracle that shares no code or assumptions with this package.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb

sk = pytest.importorskip("sklearn.ensemble")

pytestmark = pytest.mark.slow


def _int_data(n=3000, f=6, vals=12, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randint(0, vals, size=(n, f)).astype(np.float64)
    w = rng.randn(f)
    y = X @ w + 2.0 * rng.randn(n)
    return X, y


def _leaf_groups(leaf_ids):
    """Canonical partition signature: frozenset of row-index frozensets."""
    groups = {}
    for i, l in enumerate(leaf_ids):
        groups.setdefault(int(l), []).append(i)
    return {frozenset(v) for v in groups.values()}


@pytest.mark.parametrize("mode", ["strict", "rounds"])
def test_one_iteration_regression_structure_matches_sklearn(mode):
    X, y = _int_data()
    skm = sk.HistGradientBoostingRegressor(
        max_iter=1, max_leaf_nodes=15, learning_rate=0.7,
        l2_regularization=0.0, min_samples_leaf=1, max_bins=64,
        early_stopping=False, validation_fraction=None,
    )
    skm.fit(X, y)
    sk_pred = skm.predict(X)
    sk_leaves = skm._predictors[0][0].get_n_leaf_nodes()

    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(
        params={
            "objective": "regression", "num_leaves": 15, "learning_rate": 0.7,
            "verbosity": -1, "min_data_in_leaf": 1,
            "min_sum_hessian_in_leaf": 0.0, "lambda_l2": 0.0,
            "min_gain_to_split": 1e-10, "tree_growth_mode": mode,
        },
        train_set=ds,
    )
    bst.update()
    our_pred = bst.predict(X)
    tree = bst._gbdt.models[0]

    assert tree.num_leaves == sk_leaves
    # identical partition => identical Newton leaf values => identical
    # predictions (up to f32 vs f64 accumulation)
    assert np.abs(our_pred - sk_pred).max() < 1e-3
    # partition check via our own leaf assignment against value groups:
    # rows predicted identically must form the same groups in both models
    our_groups = _leaf_groups(np.round(our_pred, 6))
    sk_groups = _leaf_groups(np.round(sk_pred, 6))
    assert our_groups == sk_groups


def test_one_iteration_binary_structure_matches_sklearn():
    X, y = _int_data()
    yb = (y > np.median(y)).astype(np.float64)
    skm = sk.HistGradientBoostingClassifier(
        max_iter=1, max_leaf_nodes=15, learning_rate=0.7,
        l2_regularization=0.0, min_samples_leaf=1, max_bins=64,
        early_stopping=False, validation_fraction=None,
    )
    skm.fit(X, yb)
    sk_raw = skm.decision_function(X)

    ds = lgb.Dataset(X, label=yb)
    bst = lgb.Booster(
        params={
            "objective": "binary", "num_leaves": 15, "learning_rate": 0.7,
            "verbosity": -1, "min_data_in_leaf": 1,
            "min_sum_hessian_in_leaf": 0.0, "lambda_l2": 0.0,
            "min_gain_to_split": 1e-10, "sigmoid": 1.0,
        },
        train_set=ds,
    )
    bst.update()
    our_raw = bst.predict(X, raw_score=True)
    # same tree => same raw margins
    assert np.abs(our_raw - sk_raw).max() < 1e-3
    assert _leaf_groups(np.round(our_raw, 6)) == _leaf_groups(np.round(sk_raw, 6))


def test_multi_iteration_agreement_stays_close():
    """Beyond one tree the greedy paths can diverge on ties, but on generic
    data 10 iterations should stay numerically close to the oracle."""
    X, y = _int_data(seed=3)
    skm = sk.HistGradientBoostingRegressor(
        max_iter=10, max_leaf_nodes=15, learning_rate=0.3,
        l2_regularization=0.0, min_samples_leaf=1, max_bins=64,
        early_stopping=False, validation_fraction=None,
    )
    skm.fit(X, y)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(
        params={
            "objective": "regression", "num_leaves": 15, "learning_rate": 0.3,
            "verbosity": -1, "min_data_in_leaf": 1,
            "min_sum_hessian_in_leaf": 0.0, "lambda_l2": 0.0,
            "min_gain_to_split": 1e-10,
        },
        train_set=ds,
    )
    for _ in range(10):
        bst.update()
    r = np.corrcoef(bst.predict(X), skm.predict(X))[0, 1]
    assert r > 0.999
