"""Live introspection (round 11, docs/OBSERVABILITY.md): span tracing +
Chrome-trace export, the in-process HTTP metrics/health endpoint, fleet
metric aggregation, and the obs CLI's serve/tail/trace subcommands.

THE acceptance scenario lives at the bottom: ``curl /metrics`` during a
live ``engine.train`` returns Prometheus text with train + serve metric
families, and ``/healthz`` flips on an injected fault (``LGBMTPU_FAULT``)
without killing training.  The budget half of the round-11 contract (zero
extra dispatches/syncs/retraces with tracing and the server ON) is pinned
in test_observability.py's acceptance test.
"""

import ast
import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import metrics as obs
from lightgbm_tpu.obs import server as obs_server
from lightgbm_tpu.obs import trace as obs_trace
from lightgbm_tpu.obs.__main__ import main as obs_main, serve_snapshot


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset()
    obs.set_events_file(None)
    obs_trace.reset_trace()
    obs_trace.set_annotation_factory(None)
    yield
    obs_server.stop_server()
    obs.stop_periodic_snapshots(final_write=False)
    obs.reset()
    obs.set_events_file(None)
    obs_trace.reset_trace()
    obs_trace.set_annotation_factory(None)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# ---------------------------------------------------------------------------
# the obs package stays stdlib-only (ISSUE 6 acceptance: no jax in obs/)
# ---------------------------------------------------------------------------

def test_obs_package_imports_no_jax():
    """Static pin of the stdlib-only contract: no module under
    lightgbm_tpu/obs may import jax (or numpy — the launcher's thin
    worker processes and utils/faults.py record here without paying a
    backend import)."""
    obs_dir = Path(obs.__file__).resolve().parent
    for py in sorted(obs_dir.glob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            names = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                names = [node.module or ""]
            for name in names:
                root = name.split(".")[0]
                assert root not in ("jax", "jaxlib", "numpy"), (
                    f"{py.name} imports {name} — obs/ must stay "
                    "stdlib-only (docs/OBSERVABILITY.md)")


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------

def test_spans_nest_and_carry_attributes():
    with obs_trace.span("outer", a=1) as sp:
        sp.set(b=2)
        with obs_trace.span("inner"):
            pass
    recs = obs_trace.spans()
    inner = [s for s in recs if s["name"] == "inner"][0]
    outer = [s for s in recs if s["name"] == "outer"][0]
    assert outer["attrs"] == {"a": 1, "b": 2}
    assert inner["depth"] == 1 and inner["parent"] == outer["id"]
    assert outer["dur"] >= inner["dur"] >= 0.0


def test_record_span_is_retroactive_and_disabled_registry_silences_spans():
    obs_trace.record_span("resolved_round", 0.25, k=3)
    (rec,) = obs_trace.spans("resolved_round")
    assert rec["dur"] == 0.25 and rec["attrs"]["k"] == 3
    assert rec["ts"] <= time.time()
    obs.set_enabled(False)
    try:
        with obs_trace.span("off"):
            pass
        obs_trace.record_span("off_retro", 0.1)
        assert not obs_trace.spans("off")
        assert not obs_trace.spans("off_retro")
    finally:
        obs.set_enabled(True)


def test_chrome_trace_export_roundtrip(tmp_path):
    with obs_trace.span("tree", rounds=7):
        pass
    path = str(tmp_path / "trace.json")
    assert obs_trace.write_trace(path) == 1
    # the file IS standard Chrome trace JSON (Perfetto-loadable) ...
    doc = json.loads(Path(path).read_text())
    (ev,) = doc["traceEvents"]
    assert ev["ph"] == "X" and ev["name"] == "tree"
    assert ev["dur"] >= 0 and ev["args"]["rounds"] == 7
    # ... and round-trips through the validating loader
    doc2 = obs_trace.load_trace(path)
    assert doc2["lgbmtpu"]["spans"][0]["name"] == "tree"
    with pytest.raises(ValueError):
        obs_trace.validate_trace({"traceEvents": []})


def test_ring_overflow_spills_to_jsonl_in_order(tmp_path):
    """Round 12: ring evictions are no longer silent — with a spill sink
    armed, the OLDEST span falls off into the JSONL sidecar (in eviction
    order) and counts trace_spans_spilled_total; the ring keeps the
    newest cap spans exactly as before."""
    path = str(tmp_path / "spill.jsonl")
    obs_trace.set_ring_cap(4)
    try:
        obs_trace.enable_spill(path)
        for i in range(10):
            obs_trace.record_span(f"s{i}", 0.01, i=i)
        assert [s["name"] for s in obs_trace.spans()] == [
            f"s{i}" for i in range(6, 10)]
        obs_trace.disable_spill()
        lines = [json.loads(ln)
                 for ln in Path(path).read_text().splitlines()]
        assert [ln["name"] for ln in lines] == [f"s{i}" for i in range(6)]
        assert lines[0]["attrs"] == {"i": 0}  # full records, not summaries
        assert obs.counter("trace_spans_spilled_total").value == 6
        assert obs.counter("trace_spans_dropped_total").value == 0
    finally:
        obs_trace.disable_spill()
        obs_trace.set_ring_cap(obs_trace.TRACE_RING_CAP)


def test_spill_byte_bound_and_no_sink_count_drops(tmp_path):
    """Past max_bytes the sink stops growing and evictions degrade to the
    dropped counter; with no sink at all every eviction is a counted
    drop — either way the metrics always say what the ring lost."""
    path = str(tmp_path / "spill.jsonl")
    obs_trace.set_ring_cap(1)
    try:
        obs_trace.enable_spill(path, max_bytes=200)
        for i in range(50):
            obs_trace.record_span("pad", 0.01, i=i)
        obs_trace.disable_spill()
        spilled = obs.counter("trace_spans_spilled_total").value
        dropped = obs.counter("trace_spans_dropped_total").value
        assert spilled >= 1 and dropped >= 1
        assert spilled + dropped == 49  # every eviction accounted
        # the sink respects the bound (may overshoot by < one record)
        assert Path(path).stat().st_size < 200 + 256
        # no sink: pure drops
        obs.reset()
        obs_trace.reset_trace()
        for i in range(5):
            obs_trace.record_span("nosink", 0.01)
        assert obs.counter("trace_spans_dropped_total").value == 4
        assert obs.counter("trace_spans_spilled_total").value == 0
    finally:
        obs_trace.disable_spill()
        obs_trace.set_ring_cap(obs_trace.TRACE_RING_CAP)


def test_spill_survives_process_restart(tmp_path):
    """A relaunched process (watchdog restart / resume=auto) re-arming
    the same spill path APPENDS — the pre-crash span history the sink
    exists to preserve is not truncated.  Re-arming after a CLEAN disarm
    in the same process truncates instead: a later run's evictions must
    not be appended to (and mistaken for) a finished run's history.
    Switching paths mid-process also truncates the new file."""
    path = str(tmp_path / "spill.jsonl")
    obs_trace.set_ring_cap(1)
    try:
        obs_trace.enable_spill(path)
        for i in range(4):
            obs_trace.record_span(f"run1_{i}", 0.01)
        obs_trace.disable_spill()
        # simulate a fresh process: sink state and ring both start empty
        obs_trace._spill_path = None
        obs_trace._spill_fh = None
        obs_trace._spill_clean = False
        obs_trace.reset_trace()
        obs_trace.enable_spill(path)
        for i in range(4):
            obs_trace.record_span(f"run2_{i}", 0.01)
        obs_trace.disable_spill()
        names = [json.loads(ln)["name"]
                 for ln in Path(path).read_text().splitlines()]
        assert names == ["run1_0", "run1_1", "run1_2",
                         "run2_0", "run2_1", "run2_2"]
        # in-process re-arm after the clean disarm above: SAME path
        # truncates — run 3's sidecar holds only run 3's evictions
        obs_trace.reset_trace()
        obs_trace.enable_spill(path)
        for i in range(3):
            obs_trace.record_span(f"run3_{i}", 0.01)
        obs_trace.disable_spill()
        names = [json.loads(ln)["name"]
                 for ln in Path(path).read_text().splitlines()]
        assert names == ["run3_0", "run3_1"]
        # mid-process path switch truncates the (stale) new target
        obs_trace.reset_trace()
        other = str(tmp_path / "other.jsonl")
        Path(other).write_text('{"name": "stale"}\n')
        obs_trace.enable_spill(other)
        obs_trace.record_span("x", 0.01)
        obs_trace.record_span("y", 0.01)
        obs_trace.disable_spill()
        assert "stale" not in Path(other).read_text()
    finally:
        obs_trace.disable_spill()
        obs_trace.set_ring_cap(obs_trace.TRACE_RING_CAP)


def test_engine_train_arms_spill_next_to_trace_file(tmp_path):
    """engine.train with trace_file= arms the sidecar spill sink, so a
    run that overflows the ring leaves <trace_file>.spill.jsonl behind."""
    trace_path = str(tmp_path / "run_trace.json")
    rng = np.random.RandomState(0)
    X = rng.randn(80, 4)
    y = (X[:, 0] > 0).astype(float)
    obs_trace.set_ring_cap(2)
    try:
        lgb.train({"objective": "binary", "verbosity": -1,
                   "trace_file": trace_path},
                  lgb.Dataset(X, label=y), num_boost_round=3)
        assert obs_trace.spill_path() == trace_path + ".spill.jsonl"
        assert Path(trace_path + ".spill.jsonl").exists()
        assert obs.counter("trace_spans_spilled_total").value >= 1
        assert Path(trace_path).exists()  # the main export still lands
    finally:
        obs_trace.disable_spill()
        obs_trace.set_ring_cap(obs_trace.TRACE_RING_CAP)


def test_engine_train_disarms_spill_on_exception(tmp_path):
    """The spill sink armed at train start must be disarmed on EVERY exit
    path — a run killed by a mid-boost exception must not leave the sink
    armed process-wide, or later unrelated work's ring evictions would be
    appended to (and mistaken for) the dead run's span history."""
    trace_path = str(tmp_path / "run_trace.json")
    rng = np.random.RandomState(0)
    X = rng.randn(80, 4)
    y = (X[:, 0] > 0).astype(float)

    def _boom(env):
        raise RuntimeError("mid-boost failure")

    try:
        with pytest.raises(RuntimeError, match="mid-boost failure"):
            lgb.train({"objective": "binary", "verbosity": -1,
                       "trace_file": trace_path},
                      lgb.Dataset(X, label=y), num_boost_round=3,
                      callbacks=[_boom])
        # spill_path() keeps the last-armed path for resume semantics; the
        # armed/disarmed state is the open file handle
        assert obs_trace._spill_fh is None  # disarmed despite the raise
        assert Path(trace_path).exists()  # partial-run trace still lands
    finally:
        obs_trace.disable_spill()


def test_span_exception_close_and_mismatched_exit():
    with pytest.raises(RuntimeError):
        with obs_trace.span("boom"):
            raise RuntimeError("x")
    (rec,) = obs_trace.spans("boom")
    assert rec["attrs"]["error"] == "RuntimeError"
    assert not getattr(obs_trace._tls, "stack", [])  # stack unwound


def test_annotation_factory_mirrors_spans():
    """The jax.profiler bridge contract (utils/profiling.py installs the
    real one behind LGBMTPU_JAX_PROFILER=1): the factory's context
    manager wraps every context-manager span body."""
    entered, exited = [], []

    class _Cm:
        def __init__(self, name):
            self.name = name

        def __enter__(self):
            entered.append(self.name)

        def __exit__(self, *exc):
            exited.append(self.name)

    obs_trace.set_annotation_factory(lambda name, attrs: _Cm(name))
    with obs_trace.span("mirrored"):
        assert entered == ["mirrored"] and not exited
    assert exited == ["mirrored"]

    # the shipped factory maps iteration-carrying spans to step annotations
    from lightgbm_tpu.utils.profiling import _jax_annotation_factory
    import jax

    cm = _jax_annotation_factory("boost_round", {"iteration": 3})
    assert isinstance(cm, jax.profiler.StepTraceAnnotation)
    cm2 = _jax_annotation_factory("train", {})
    assert isinstance(cm2, jax.profiler.TraceAnnotation)


# ---------------------------------------------------------------------------
# HTTP endpoint lifecycle
# ---------------------------------------------------------------------------

def test_server_routes_and_clean_shutdown():
    obs.counter("t_live_total").inc(2)
    obs.gauge("t_live_gauge").set(1.5)
    obs.histogram(obs.labeled("t_live_ms", bucket=128)).observe(3.0)
    obs.event("t_live", n=1)
    obs.event("t_live", n=2)
    srv = obs_server.MetricsServer(port=0).start()
    try:
        code, prom = _get(srv.url("/metrics"))
        assert code == 200
        assert "lgbmtpu_t_live_total 2" in prom
        assert 'lgbmtpu_t_live_ms{bucket="128",quantile="0.5"} 3.0' in prom
        code, snap_body = _get(srv.url("/snapshot"))
        snap = json.loads(snap_body)
        obs.validate_snapshot(snap)
        assert snap["counters"]["t_live_total"] == 2
        code, hz = _get(srv.url("/healthz"))
        assert code == 200 and json.loads(hz)["status"] == "ok"
        code, ev = _get(srv.url("/events?tail=1&kind=t_live"))
        recs = [json.loads(line) for line in ev.splitlines()]
        assert len(recs) == 1 and recs[0]["n"] == 2
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.url("/nope"))
    finally:
        srv.stop()
    # clean shutdown: the port no longer accepts connections
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(srv.url("/metrics"), timeout=2)
    srv.stop()  # idempotent


def test_server_concurrent_gets():
    obs.counter("t_conc_total").inc()
    srv = obs_server.MetricsServer(port=0).start()
    results, errors = [], []

    def hit():
        try:
            results.append(_get(srv.url("/metrics"))[0])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    try:
        threads = [threading.Thread(target=hit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors and results == [200] * 8
    finally:
        srv.stop()


def test_server_port_in_use_falls_back_to_ephemeral():
    first = obs_server.MetricsServer(port=0).start()
    try:
        second = obs_server.MetricsServer(port=first.port).start()
        try:
            assert second.fell_back
            assert second.port != first.port
            assert _get(second.url("/metrics"))[0] == 200
            assert obs.counter(
                "metrics_server_port_fallbacks_total").value == 1
        finally:
            second.stop()
    finally:
        first.stop()


def test_healthz_flips_degraded_then_unhealthy():
    srv = obs_server.MetricsServer(port=0).start()
    try:
        assert json.loads(_get(srv.url("/healthz"))[1])["status"] == "ok"
        obs.counter("degrade_disabled_total").inc()
        code, body = _get(srv.url("/healthz"))
        body = json.loads(body)
        assert code == 200 and body["status"] == "degraded"
        assert body["problems"][0]["counter"] == "degrade_disabled_total"
        obs.counter("train_nonfinite_errors_total").inc()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url("/healthz"))
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())["status"] == "unhealthy"
    finally:
        srv.stop()


def test_singleton_start_is_idempotent_and_env_gated(monkeypatch):
    assert obs_server.maybe_start(None) is None  # no opt-in anywhere
    monkeypatch.setenv("LGBMTPU_METRICS_PORT", "-1")
    assert obs_server.maybe_start(None) is None  # explicit off
    monkeypatch.setenv("LGBMTPU_METRICS_PORT", "0")
    srv = obs_server.maybe_start(None)
    assert srv is not None and srv.running
    assert obs_server.start_server(0) is srv  # one process, one endpoint
    assert obs_server.maybe_start(12345) is srv
    obs_server.stop_server()
    assert obs_server.get_server() is None


# ---------------------------------------------------------------------------
# fleet metrics aggregation
# ---------------------------------------------------------------------------

def _rank_snapshot_file(tmp_path, rank, counters, gauge, samples):
    reg = obs.Registry()
    reg._rank = rank
    for name, v in counters.items():
        c = reg.counter(name)
        c._value = v  # direct: avoid the global-enabled gate
    reg.gauge("fleet_gauge")._value = gauge
    h = reg.histogram("fleet_ms")
    for s in samples:
        h.count += 1
        h.total += s
        h.min = s if h.min is None else min(h.min, s)
        h.max = s if h.max is None else max(h.max, s)
        h._samples.append(s)
    path = str(tmp_path / f"worker{rank}.metrics.json")
    obs.write_snapshot(path, reg.snapshot(include_samples=True))
    return path


def test_fleet_merge_sums_counters_maxes_gauges_merges_reservoirs(tmp_path):
    p0 = _rank_snapshot_file(tmp_path, 0, {"train_boost_rounds_total": 5},
                             2.0, [1.0, 2.0])
    p1 = _rank_snapshot_file(tmp_path, 1, {"train_boost_rounds_total": 7},
                             9.0, [3.0, 4.0])
    out = str(tmp_path / "fleet_metrics.json")
    fleet = obs.merge_snapshot_files([p0, p1], out)
    obs.validate_fleet_metrics(fleet)
    assert fleet["num_ranks"] == 2
    assert set(fleet["ranks"]) == {"0", "1"}
    agg = fleet["aggregate"]
    assert agg["counters"]["train_boost_rounds_total"] == 12  # summed
    assert agg["gauges"]["fleet_gauge"] == 9.0  # maxed
    h = agg["histograms"]["fleet_ms"]
    assert h["count"] == 4 and h["sum"] == 10.0
    assert h["min"] == 1.0 and h["max"] == 4.0
    assert h["p99"] == 4.0  # recomputed from the MERGED reservoir
    # the written artifact round-trips
    assert obs.load_fleet_metrics(out)["num_ranks"] == 2
    # per-rank labels in the Prometheus output, aggregate unlabeled
    prom = obs.render_prometheus_fleet(fleet)
    assert "lgbmtpu_train_boost_rounds_total 12" in prom
    assert 'lgbmtpu_train_boost_rounds_total{rank="0"} 5' in prom
    assert 'lgbmtpu_train_boost_rounds_total{rank="1"} 7' in prom
    assert 'lgbmtpu_fleet_ms_count{rank="1"} 2' in prom


def test_fleet_merge_survives_crashed_ranks(tmp_path):
    """The kill-path contract: rank 1 died before its first periodic
    write (no file), rank 2 left a torn file — the merge still yields a
    schema-valid artifact with the surviving rank plus the aggregate."""
    p0 = _rank_snapshot_file(tmp_path, 0, {"train_boost_rounds_total": 3},
                             1.0, [0.5])
    p1 = str(tmp_path / "worker1.metrics.json")  # never written
    p2 = str(tmp_path / "worker2.metrics.json")
    Path(p2).write_text('{"schema": "lgbmtpu-metr')  # torn mid-crash
    out = str(tmp_path / "fleet_metrics.json")
    fleet = obs.merge_snapshot_files([p0, p1, p2], out)
    obs.validate_fleet_metrics(fleet)
    assert fleet["num_ranks"] == 1
    assert sorted(fleet["skipped"]) == ["worker1.metrics.json",
                                       "worker2.metrics.json"]
    assert fleet["aggregate"]["counters"]["train_boost_rounds_total"] == 3


def test_launcher_aggregate_fleet_metrics_on_partial_fleet(tmp_path):
    """parallel/launcher.py's exit-path helper over a fleet where one
    rank crashed pre-write: file written, valid, one entry + aggregate."""
    from lightgbm_tpu.parallel.launcher import aggregate_fleet_metrics

    _rank_snapshot_file(tmp_path, 0, {"launcher_worker_spawns_total": 2},
                        0.0, [1.0])
    out = aggregate_fleet_metrics(str(tmp_path), num_machines=2)
    fleet = obs.load_fleet_metrics(out)
    assert fleet["num_ranks"] == 1 and "0" in fleet["ranks"]


def test_periodic_snapshot_writer_writes_immediately_and_stops(tmp_path):
    path = str(tmp_path / "rank.metrics.json")
    obs.counter("t_periodic_total").inc(4)
    obs.histogram("t_periodic_ms").observe(1.0)
    obs.start_periodic_snapshots(path, period_s=30.0)  # long period:
    # the immediate first write is the property under test (a worker dying
    # in round 1 must still leave a file)
    deadline = time.monotonic() + 10
    while not Path(path).exists() and time.monotonic() < deadline:
        time.sleep(0.01)
    snap = obs.load_snapshot(path)
    assert snap["counters"]["t_periodic_total"] == 4
    assert snap["histograms"]["t_periodic_ms"]["samples"] == [1.0]
    obs.counter("t_periodic_total").inc()
    obs.stop_periodic_snapshots()  # final flush makes the file exact
    assert obs.load_snapshot(path)["counters"]["t_periodic_total"] == 5


# ---------------------------------------------------------------------------
# obs CLI: serve / tail / trace subcommands + strict validation
# ---------------------------------------------------------------------------

def test_cli_dump_invalid_snapshot_exits_2_with_no_partial_report(
        tmp_path, capsys):
    bad = tmp_path / "bad.json"
    # schema header valid, body poisoned: the old CLI would print a
    # partial report before dying — now it must exit 2 with NO stdout
    bad.write_text(json.dumps({
        "schema": obs.SCHEMA, "ts": 1.0, "counters": {"x": "NaN-ish"},
        "gauges": {}, "histograms": {}, "events_total": 0}))
    assert obs_main([str(bad)]) == 2
    out = capsys.readouterr()
    assert out.out == ""
    assert "error" in out.err


def test_cli_trace_subcommand(tmp_path, capsys):
    with obs_trace.span("cli_span", n=1):
        pass
    src = str(tmp_path / "t.json")
    obs_trace.write_trace(src)
    # validate + re-emit a saved trace
    assert obs_main(["trace", src]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["traceEvents"][0]["name"] == "cli_span"
    # live-ring export to a file
    dst = str(tmp_path / "out.json")
    assert obs_main(["trace", "-o", dst]) == 0
    assert obs_trace.load_trace(dst)["traceEvents"]
    # invalid input exits 2
    (tmp_path / "nottrace.json").write_text("{}")
    assert obs_main(["trace", str(tmp_path / "nottrace.json")]) == 2


def test_cli_serve_subcommand_over_snapshot_file(tmp_path):
    obs.counter("t_serve_total").inc(6)
    obs.counter("degrade_disabled_total").inc()  # saved health: degraded
    spath = str(tmp_path / "snap.json")
    obs.write_snapshot(spath)
    epath = tmp_path / "events.jsonl"
    epath.write_text(json.dumps({"ts": 1.0, "kind": "boost_round"}) + "\n")
    srv = serve_snapshot(spath, port=0, events_path=str(epath))
    try:
        code, prom = _get(srv.url("/metrics"))
        assert code == 200 and "lgbmtpu_t_serve_total 6" in prom
        code, hz = _get(srv.url("/healthz"))
        assert json.loads(hz)["status"] == "degraded"
        code, ev = _get(srv.url("/events?tail=5"))
        assert json.loads(ev.splitlines()[0])["kind"] == "boost_round"
    finally:
        srv.stop()
    notsnap = tmp_path / "notsnap.json"
    notsnap.write_text("{}")
    with pytest.raises(ValueError):
        serve_snapshot(str(notsnap))
    assert obs_main(["serve", str(tmp_path / "missing.json")]) == 2


def test_cli_tail_subcommand(tmp_path, capsys):
    p = tmp_path / "events.jsonl"
    lines = [{"ts": float(i), "kind": "boost_round", "iteration": i}
             for i in range(5)]
    p.write_text("".join(json.dumps(r) + "\n" for r in lines)
                 + '{"ts": 9.0, "kind": "torn')  # crashed-worker tail
    assert obs_main(["tail", str(p), "-n", "2"]) == 0
    out = [json.loads(line) for line in capsys.readouterr().out.splitlines()]
    assert [r["iteration"] for r in out] == [3, 4]  # newest N, torn skipped
    assert obs_main(["tail", str(p), "-n", "10", "--kind", "boost_round"]
                    ) == 0
    assert len(capsys.readouterr().out.splitlines()) == 5
    # the `tail -n 0` idiom prints NO history, not the whole file
    assert obs_main(["tail", str(p), "-n", "0"]) == 0
    assert capsys.readouterr().out == ""
    assert obs_main(["tail", str(tmp_path / "missing.jsonl")]) == 2


# ---------------------------------------------------------------------------
# ACCEPTANCE: /metrics during a LIVE engine.train; /healthz flips on an
# injected fault without killing training
# ---------------------------------------------------------------------------

def test_metrics_endpoint_live_during_train_and_healthz_fault_flip(
        monkeypatch, tmp_path):
    import jax.numpy as jnp

    from lightgbm_tpu.utils import degrade, faults

    rng = np.random.RandomState(11)
    X = rng.randn(600, 6)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)

    seen = {}

    def mid_train_probe(env):
        if env.iteration == 1 and "prom" not in seen:
            srv = obs_server.get_server()
            assert srv is not None, "metrics_port= did not start the server"
            seen["port"] = srv.port
            _, seen["prom"] = _get(srv.url("/metrics"))
            _, hz = _get(srv.url("/healthz"))
            seen["health_before"] = json.loads(hz)["status"]
            # injected fault (LGBMTPU_FAULT harness): the Pallas histogram
            # dispatcher fires mid-run and degrades to XLA — training must
            # survive, /healthz must flip
            monkeypatch.setenv("LGBMTPU_FAULT", "pallas_hist:0")
            faults.reset()
            from lightgbm_tpu.ops.histogram import histogram_multi

            n, f, tile, bins = 128, 2, 2, 8
            histogram_multi(
                jnp.asarray(rng.randint(0, bins, (n, f)), jnp.int16),
                jnp.ones((n,), jnp.float32), jnp.ones((n,), jnp.float32),
                jnp.ones((n,), bool),
                jnp.zeros((n,), jnp.int32), 0, tile, bins)
            monkeypatch.delenv("LGBMTPU_FAULT")
            faults.reset()
            code, hz = _get(srv.url("/healthz"))
            seen["health_after"] = json.loads(hz)["status"]
            seen["code_after"] = code

    mid_train_probe.order = 0

    degrade.reset()
    try:
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 7, "verbosity": -1,
             "metrics_port": 0,
             "trace_file": str(tmp_path / "train_trace.json")},
            lgb.Dataset(X, label=y), num_boost_round=4,
            callbacks=[mid_train_probe])
    finally:
        degrade.reset()

    # training survived the fault and finished every round
    assert bst.current_iteration() == 4
    # /metrics DURING training carried the train family (serve counters
    # appear once predict runs; assert them post-predict below)
    assert "lgbmtpu_train_boost_rounds_total" in seen["prom"]
    assert "lgbmtpu_device_dispatches_total" in seen["prom"]
    assert seen["health_before"] == "ok"
    assert seen["health_after"] == "degraded" and seen["code_after"] == 200

    # the engine-started server is still live after train (long-lived
    # serving processes keep scraping it); serve family appears once a
    # predict has run
    bst.predict(X, raw_score=True)
    bst.predict(X, raw_score=True)
    srv = obs_server.get_server()
    assert srv is not None and srv.port == seen["port"]
    _, prom = _get(srv.url("/metrics"))
    assert "lgbmtpu_predict_requests_total" in prom
    assert 'lgbmtpu_predict_warm_latency_ms{bucket="' in prom
    obs_server.stop_server()

    # trace_file= left a Perfetto-loadable trace covering the run
    doc = obs_trace.load_trace(str(tmp_path / "train_trace.json"))
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert "train" in names and "boost_round" in names
