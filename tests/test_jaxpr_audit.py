"""Tier-1 jaxpr-audit gate + per-J-rule fixtures (docs/ANALYSIS.md
"Jaxpr audit layer").

Gate half: every registered contract (analysis/contracts.py) must verify
clean on the container CPU — the sharded fused round shows exactly the
declared collectives (ONE large merge per strategy) on the declared mesh
axis, every live donated buffer is consumable, zero f64 casts, zero host
callbacks, the live-set estimate under budget — and the runtime
DispatchCounter ledger agrees the collectives all rode the single
per-round dispatch.  This is the static gate for the regression class
the AST rules cannot see (the shared ``_run_fused_rounds`` driver
dispatches through a closure, R1/R6/R13 static-limits note).

Fixture half: each J rule is exercised on a deliberately broken tiny
executable (all under 8192 rows, so windowed fixtures stay on one
W-ladder rung), mirroring tests/test_jaxlint_rules.py's
positive/negative/waiver pattern.
"""

import numpy as np
import pytest

from lightgbm_tpu.analysis import jaxpr_audit
from lightgbm_tpu.analysis.contracts import CONTRACTS, Contract, Target


# ---------------------------------------------------------------------------
# the gate: one full audit per session, asserted from every angle
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def report():
    return jaxpr_audit.run_jaxpr_audit()


def test_contract_catalogue_pins_the_flagships():
    assert {
        "windowed_round_float", "windowed_round_quantized",
        "windowed_round_sharded_psum", "windowed_round_sharded_scatter",
        "windowed_round_hierarchical_psum",
        "windowed_round_hierarchical_voting",
        "windowed_round_2d_float", "windowed_round_2d_quantized",
        "predict_warm_single", "predict_warm_multiclass",
        "predict_warm_converted", "predict_coalesced_bucket",
        "ooc_root_chunk", "ooc_split_chunk", "continual_refit_leaves",
        "fleet_round_batched",
    } <= set(CONTRACTS)


def test_all_contracts_verify_clean(report):
    assert report.ok, (
        "jaxpr-audit findings (fix the executable or waive in "
        "analysis/contracts.py with a reason):\n"
        + "\n".join(f.format() for f in report.findings))


def test_sharded_rounds_have_exactly_one_large_collective(report):
    """The headline invariant: per merge strategy, ONE collective moves
    histogram-sized bytes; everything else is scalar protocol traffic."""
    for r in report.results:
        if not r.name.startswith("windowed_round_sharded"):
            continue
        assert r.detail.get("large_collectives") == 1, (r.name, r.detail)


def test_2d_round_histogram_phase_never_crosses_the_feature_axis(report):
    """The wide-F headline: in the 2-D round, the histogram phase is a
    row-axis psum ALONE — the owned feature block's histograms are
    complete by layout, so the sequence shows ZERO hist-sized
    feature-axis traffic, and the per-axis byte bill proves the feature
    axis carries only the go/no-go row broadcast + election scalars."""
    from lightgbm_tpu.analysis.contracts import _2D_FEATURE_BUDGET
    for name in ("windowed_round_2d_float", "windowed_round_2d_quantized"):
        r = {x.name: x for x in report.results}[name]
        toks = r.detail["collectives"]
        # exactly one @data-only psum (the histogram merge) and it is the
        # largest collective in the round
        data_only = [t for t in toks if t == "psum@data"]
        assert len(data_only) == 3, (name, toks)  # 2 protocol + 1 hist
        bills = r.detail["axis_bytes"]
        assert bills["feature"] <= _2D_FEATURE_BUDGET, (name, bills)
        assert r.detail["feature_bytes"] == bills["feature"]
        # the row axis carries the histogram merge: orders of magnitude
        # more bytes than the feature axis at any realistic shape
        assert bills["data"] > bills["feature"], (name, bills)


def test_single_device_bodies_are_collective_free(report):
    for r in report.results:
        if r.name in ("windowed_round_float", "windowed_round_quantized",
                      "predict_warm_single", "predict_warm_multiclass",
                      "predict_warm_converted", "predict_coalesced_bucket",
                      "ooc_root_chunk", "ooc_split_chunk",
                      "continual_refit_leaves", "fleet_round_batched"):
            assert r.detail.get("collectives") == [], (r.name, r.detail)


def test_coalesced_dispatch_is_the_warm_predict_family():
    """ISSUE 13: the serving runtime's coalesced dispatch must be the
    SAME traced executable family as warm predict — pinned two ways: the
    runtime's selector resolves to the very predict_ops functions the
    warm contracts audit (identity, so the contract traces the serving
    loop's real dispatch), and the audited body is collective-free /
    transfer-free like its warm siblings (the report gate above)."""
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.ops import predict as predict_ops
    from lightgbm_tpu.serve.runtime import audit_dispatch_fn

    assert audit_dispatch_fn(1) is predict_ops.predict_raw_values
    assert audit_dispatch_fn(4) is predict_ops.predict_raw_multiclass
    assert GBDT._coalesced_raw_fn(1) is predict_ops.predict_raw_values
    assert GBDT._coalesced_raw_fn(3) is predict_ops.predict_raw_multiclass


def test_continual_refit_is_one_donated_collective_free_dispatch(report):
    """ISSUE 14: the continual refit dispatch — resolved through the
    runner's own builder (continual.refit.audit_refit_fn) — is ONE
    donated executable: zero collectives (J1, single-device), the
    donated leaf table consumed and aliased in the lowering (J2), and
    transfer-free (J5, the report gate above)."""
    r = {x.name: x for x in report.results}["continual_refit_leaves"]
    assert r.detail.get("collectives") == []
    assert r.detail.get("live_donated_leaves") == 1
    assert r.detail.get("aliased_in_lowering") == 1


def test_donations_all_consumable(report):
    """J2 detail: every live donated leaf structurally matched an output
    (and on the single-device lowering, actually carries the aliasing
    attr — the sharded CPU lowering drops aliasing wholesale, which is
    why the structural check is the platform-independent half)."""
    for r in report.results:
        live = r.detail.get("live_donated_leaves")
        if not live:
            continue
        if r.name.startswith(("windowed_round_sharded",
                              "windowed_round_hierarchical",
                              "windowed_round_2d")):
            continue  # aliasing attrs absent in multi-device CPU lowering
        assert r.detail.get("aliased_in_lowering") == live, (r.name, r.detail)


def test_ledger_crosscheck_agrees(report):
    """The sanitizer cross-check: the tiny sharded training's runtime
    ledger shows 1 dispatch / 0 blocking syncs per round, so every
    audited collective rode the one donated dispatch."""
    for merge in ("psum", "scatter"):
        summary = report.ledger[merge]
        assert summary["dispatches"] == summary["rounds"] > 0, summary
        assert summary["host_syncs"] == 0, summary
        assert summary["collectives_per_round"] == len(
            CONTRACTS[f"windowed_round_sharded_{merge}"].collectives)


def test_windowed_fixture_shapes_stay_on_one_rung():
    """All audited windowed fixtures sit under 8192 rows — the floor
    W-ladder rung — so the traced executable is the same one-rung round
    the budget pins exercise."""
    from lightgbm_tpu.analysis.contracts import _N, _W
    from lightgbm_tpu.ops.treegrow_windowed import _window_size
    assert _N < 8192
    assert _window_size(max(_N // 2, 1), _N) == _W == 8192


# ---------------------------------------------------------------------------
# per-rule fixtures: deliberately broken executables
# ---------------------------------------------------------------------------

def _fixture_contract(name, build, *, collectives=(), donated_args=(),
                      max_const_bytes=1 << 16, max_live_bytes=1 << 22,
                      waivers=None):
    return Contract(
        name=name, description="fixture", build=build,
        collectives=tuple(collectives), donated_args=tuple(donated_args),
        max_const_bytes=max_const_bytes, max_live_bytes=max_live_bytes,
        family="", spine=(0, 0), waivers=dict(waivers or {}),
        file=__file__, line=0)


def _loopback_shard_map(body, n_out=1):
    import jax
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.parallel.compat import shard_map
    from lightgbm_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(min(4, len(jax.devices())))
    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("data"),),
        out_specs=tuple([P()] * n_out) if n_out > 1 else P(),
        check_vma=False))


def test_j1_two_collective_round_fails():
    """A deliberately TWO-psum round body against a one-psum declaration:
    the exact regression (a second in-dispatch merge) R13 cannot see
    through the closure dispatch."""
    import jax
    import jax.numpy as jnp

    def body(x):  # x: (rows, bins) shard
        h = jax.lax.psum(x, "data")            # the declared merge
        h2 = jax.lax.psum(h * 2.0, "data")     # the smuggled second one
        return h + h2

    fn = _loopback_shard_map(body)
    c = _fixture_contract(
        "fixture_two_collectives",
        lambda: Target(fn, (jax.ShapeDtypeStruct((256, 32), jnp.float32),),
                       {}),
        collectives=("psum@data",))
    res = jaxpr_audit.audit_contract(c)
    assert any(f.rule == "J1" for f in res.findings), res.findings
    assert "sequence mismatch" in " ".join(
        f.message for f in res.findings if f.rule == "J1")


def test_j1_undeclared_axis_fails():
    """A collective on an axis the mesh module never declared."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from lightgbm_tpu.parallel.compat import shard_map

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("rows",))
    fn = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "rows"), mesh=mesh,
        in_specs=(P("rows"),), out_specs=P(), check_vma=False))
    c = _fixture_contract(
        "fixture_bad_axis",
        lambda: Target(fn, (jax.ShapeDtypeStruct((64,), jnp.float32),), {}),
        collectives=("psum@rows",))
    res = jaxpr_audit.audit_contract(c)
    assert any(f.rule == "J1" and "undeclared axis" in f.message
               for f in res.findings), res.findings


@pytest.mark.filterwarnings("ignore:Some donated buffers were not usable")
def test_j2_dropped_donation_fails():
    """A donated buffer whose aval matches no output: XLA would warn once
    and copy forever — the audit fails it statically."""
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x):
        return jnp.sum(state) + jnp.sum(x)  # scalar out: (128,128) donor dies

    c = _fixture_contract(
        "fixture_dropped_donation",
        lambda: Target(step, (jax.ShapeDtypeStruct((128, 128), jnp.float32),
                              jax.ShapeDtypeStruct((128, 128), jnp.float32)),
                       {}),
        donated_args=(0,))
    res = jaxpr_audit.audit_contract(c)
    assert any(f.rule == "J2" for f in res.findings), res.findings


def test_j2_consumed_donation_passes():
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x):
        return state + x

    c = _fixture_contract(
        "fixture_consumed_donation",
        lambda: Target(step, (jax.ShapeDtypeStruct((64, 64), jnp.float32),
                              jax.ShapeDtypeStruct((64, 64), jnp.float32)),
                       {}),
        donated_args=(0,))
    res = jaxpr_audit.audit_contract(c)
    assert res.ok, res.findings
    assert res.detail["aliased_in_lowering"] == 1


def test_j3_f64_leak_fails():
    """An f64 promotion inside the body (traced under x64 so the cast is
    real, as a chip run with x64 enabled would see it)."""
    import jax
    import jax.numpy as jnp

    with jax.experimental.enable_x64():
        f = jax.jit(lambda x: x.astype(jnp.float64).sum())
        c = _fixture_contract(
            "fixture_f64_leak",
            lambda: Target(f, (jax.ShapeDtypeStruct((64,), jnp.float32),),
                           {}))
        res = jaxpr_audit.audit_contract(c)
    assert any(f_.rule == "J3" for f_ in res.findings), res.findings


def test_j4_host_callback_fails():
    import jax
    import jax.numpy as jnp

    def f(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct((64,), jnp.float32), x)
        return y.sum()

    c = _fixture_contract(
        "fixture_callback",
        lambda: Target(jax.jit(f),
                       (jax.ShapeDtypeStruct((64,), jnp.float32),), {}))
    res = jaxpr_audit.audit_contract(c)
    assert any(f_.rule == "J4" for f_ in res.findings), res.findings


def test_j5_oversized_baked_constant_fails():
    """A closure-captured concrete array above the contract threshold:
    baked into the trace, re-materialized every dispatch."""
    import jax
    import jax.numpy as jnp

    table = jnp.asarray(np.random.RandomState(0).randn(4096, 8),
                        jnp.float32)  # 128 KiB > the 64 KiB default

    def f(x):
        return x @ table.T

    c = _fixture_contract(
        "fixture_baked_constant",
        lambda: Target(jax.jit(f),
                       (jax.ShapeDtypeStruct((16, 8), jnp.float32),), {}))
    res = jaxpr_audit.audit_contract(c)
    assert any(f_.rule == "J5" and "baked constant" in f_.message
               for f_ in res.findings), res.findings


def test_j6_live_set_budget_fails_on_blowup():
    """An O(L*F*B)-style intermediate blowing a tight budget."""
    import jax
    import jax.numpy as jnp

    def f(x):
        big = jnp.broadcast_to(x[:, None], (4096, 512)) * 2.0  # 8 MB f32
        return big.sum()

    c = _fixture_contract(
        "fixture_live_blowup",
        lambda: Target(jax.jit(f),
                       (jax.ShapeDtypeStruct((4096,), jnp.float32),), {}),
        max_live_bytes=1 << 20)
    res = jaxpr_audit.audit_contract(c)
    assert any(f_.rule == "J6" for f_ in res.findings), res.findings


def test_waiver_suppresses_with_reason_and_p0_without():
    import jax
    import jax.numpy as jnp

    def f(x):
        big = jnp.broadcast_to(x[:, None], (4096, 512)) * 2.0
        return big.sum()

    build = lambda: Target(  # noqa: E731
        jax.jit(f), (jax.ShapeDtypeStruct((4096,), jnp.float32),), {})
    waived = jaxpr_audit.audit_contract(_fixture_contract(
        "fixture_waived", build, max_live_bytes=1 << 20,
        waivers={"J6": "fixture: the blowup is the point"}))
    assert waived.ok and len(waived.waived) == 1

    bad = jaxpr_audit.audit_contract(_fixture_contract(
        "fixture_bad_waiver", build, max_live_bytes=1 << 20,
        waivers={"J6": "", "J99": "no such rule"}))
    assert sum(1 for f in bad.findings if f.rule == "P0") == 2
    assert any(f.rule == "J6" for f in bad.findings)  # empty reason ≠ waived


def test_cli_jaxpr_selection_and_exit_codes():
    from lightgbm_tpu.analysis.__main__ import main
    assert main(["--list-contracts"]) == 0
    assert main(["--jaxpr", "--contract", "ooc_root_chunk",
                 "--no-runtime"]) == 0
    assert main(["--jaxpr", "--contract", "no_such_contract"]) == 2


# ---------------------------------------------------------------------------
# J7: hbm-sweep-bound (ISSUE 11 — the megakernel's 3->1 claim, pinned)
# ---------------------------------------------------------------------------

def test_j7_megakernel_vs_three_pass_sweep_pins(report):
    """The headline: at the W=N sweep fixture, the megakernel round reads
    the bin matrix ONCE (+ the tile/f decisions-gather epsilon) where the
    legacy three-pass round reads it three times — pinned on the traced
    IR, not hoped."""
    detail = {r.name: r.detail for r in report.results}
    mk = detail["windowed_round_megakernel"]["bin_sweeps"]
    legacy = detail["windowed_round_three_pass_sweeps"]["bin_sweeps"]
    assert 1.0 <= mk <= 1.1, mk
    assert 3.0 <= legacy <= 3.2, legacy
    assert legacy / mk > 2.5  # the 3->1 fusion, as an IR-level ratio


def test_j7_sharded_megakernel_keeps_merge_protocol(report):
    """The sharded megakernel round's collective sequence is IDENTICAL to
    the legacy sharded round's — the single in-dispatch histogram merge
    unchanged (the ISSUE's sharded constraint)."""
    detail = {r.name: r.detail for r in report.results}
    assert (detail["windowed_round_sharded_megakernel_psum"]["collectives"]
            == detail["windowed_round_sharded_psum"]["collectives"])
    assert detail["windowed_round_sharded_megakernel_psum"][
        "large_collectives"] == 1


def test_j7_extra_sweep_fails():
    """A deliberately second full read of the bin matrix (the regression
    class: a new bin consumer added OUTSIDE the kernel) breaks the
    1-sweep budget."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    def round_body(bins, rows):
        w = bins[:, rows].T            # sweep 1: the window gather
        again = bins[:, rows].T        # sweep 2: the smuggled re-read
        return (w.astype(jnp.int32).sum()
                + again.astype(jnp.int32).sum())

    n, f = 1024, 16
    c = dataclasses.replace(
        _fixture_contract(
            "fixture_extra_sweep",
            lambda: Target(jax.jit(round_body),
                           (jax.ShapeDtypeStruct((f, n), jnp.int16),
                            jax.ShapeDtypeStruct((n,), jnp.int32)), {})),
        bin_arg=0, max_bin_sweeps=2.5)
    res = jaxpr_audit.audit_contract(c)
    assert any(f.rule == "J7" for f in res.findings), res.findings
    assert res.detail["bin_sweeps"] > 2.5


def _axis_mapped_ici_sequence(tokens):
    """Map a hierarchical round's collective tokens onto the legacy
    single-axis vocabulary: drop dcn-only collectives (the top-k
    election), rename both-axes scalar merges and ici merges to the
    legacy 'data' axis."""
    out = []
    for t in tokens:
        name, _, axes = t.partition("@")
        ax = set(axes.split(","))
        if ax == {"dcn"}:
            continue  # the election block: dcn-only, by design
        assert "ici" in ax, t
        out.append(f"{name}@data")
    return out


def test_hierarchical_ici_sequence_equals_legacy_sharded(report):
    """ISSUE 15 acceptance: per slice, the hierarchical round's ici
    collective sequence is IDENTICAL to the legacy sharded round's —
    the intra-slice merge (J1 sequence) is unchanged; only the dcn
    election block is new."""
    detail = {r.name: r.detail for r in report.results}
    for hier, legacy in (
            ("windowed_round_hierarchical_psum",
             "windowed_round_sharded_psum"),
            ("windowed_round_hierarchical_voting",
             "windowed_round_sharded_scatter")):
        assert (_axis_mapped_ici_sequence(detail[hier]["collectives"])
                == detail[legacy]["collectives"]), (hier, legacy)


def test_hierarchical_dcn_bytes_pinned(report):
    """The cross-slice byte bill: both hierarchical contracts carry a
    dcn_bytes detail under the declared dcn_max_bytes budget — ≤ top-k
    histograms' worth per round — and exactly TWO large collectives
    (one intra-slice merge + one top-k exchange), the dcn one bounded."""
    from lightgbm_tpu.analysis.contracts import (
        _BINS, _HIER_TOPK, _TILE)

    k_hist_bytes = 2 * _TILE * 3 * _HIER_TOPK * _BINS * 4
    for name in ("windowed_round_hierarchical_psum",
                 "windowed_round_hierarchical_voting"):
        c = CONTRACTS[name]
        r = {x.name: x for x in report.results}[name]
        assert c.dcn_max_bytes is not None
        assert 0 < r.detail["dcn_bytes"] <= c.dcn_max_bytes, r.detail
        # the election's histogram payload dominates; scalar slack only
        assert r.detail["dcn_bytes"] <= k_hist_bytes + 1024, r.detail
        assert r.detail["large_collectives"] == 2, r.detail


def test_dcn_bytes_fixture_full_histogram_over_dcn_fails():
    """A deliberately full-F histogram psum over the dcn axis against a
    top-k-sized budget: the regression class the hierarchical merge
    exists to prevent (and jaxlint R17 flags at the source level)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.analysis.jaxpr_audit import dcn_axis_bytes
    from lightgbm_tpu.parallel.compat import shard_map
    from lightgbm_tpu.parallel.mesh import make_mesh_hierarchical

    mesh = make_mesh_hierarchical(2, min(2, max(1, jax.device_count() // 2)))

    def body(h):  # (C, 3, F, B) full histogram block
        h = jax.lax.psum(h, "ici")          # intra-slice: fine
        return jax.lax.psum(h, "dcn")       # full-F over DCN: the bug

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(),), out_specs=P(),
        check_vma=False))
    c = dataclasses.replace(
        _fixture_contract(
            "fixture_full_hist_over_dcn",
            lambda: Target(
                fn, (jax.ShapeDtypeStruct((8, 3, 64, 32), jnp.float32),),
                {}),
            collectives=("psum@ici", "psum@dcn")),
        dcn_max_bytes=4096)
    res = jaxpr_audit.audit_contract(c)
    assert any(f.rule == "J1" and "dcn" in f.message
               for f in res.findings), res.findings
    assert res.detail["dcn_bytes"] == 8 * 3 * 64 * 32 * 4
    # and the helper counts only dcn-crossing collectives
    assert dcn_axis_bytes([("psum", ("ici",), 100),
                           ("psum", ("ici", "dcn"), 8),
                           ("psum", ("dcn",), 50)]) == 58


def test_j7_detail_rides_the_artifact_verdict():
    """bench.py embeds verdict(); the J7-pinned contracts must appear in
    it so chip artifact rows carry the sweep proof next to J1-J6."""
    from lightgbm_tpu.analysis.contracts import CONTRACTS
    pinned = [n for n, c in CONTRACTS.items() if c.max_bin_sweeps]
    assert "windowed_round_megakernel" in pinned
    assert "windowed_round_three_pass_sweeps" in pinned
