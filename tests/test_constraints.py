"""Monotone & interaction constraints, extra_trees, feature_fraction_bynode.

Mirrors reference coverage in tests/python_package_test/test_engine.py
(test_monotone_constraints: pointwise monotonicity of predictions;
test_interaction_constraints: only allowed feature pairs co-occur on paths).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _is_monotone(bst, f_idx, sign, n_grid=50, n_probe=20, seed=0):
    """Check predictions are monotone in feature f_idx pointwise on a grid."""
    rng = np.random.RandomState(seed)
    f = bst.num_feature()
    base = rng.randn(n_probe, f)
    grid = np.linspace(-2.5, 2.5, n_grid)
    for i in range(n_probe):
        rows = np.repeat(base[i : i + 1], n_grid, axis=0)
        rows[:, f_idx] = grid
        p = bst.predict(rows)
        d = np.diff(p)
        if sign > 0 and (d < -1e-10).any():
            return False
        if sign < 0 and (d > 1e-10).any():
            return False
    return True


def _make_monotone_data(n=4000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    # y increasing in x0, decreasing in x1, arbitrary in x2
    y = (
        2.0 * X[:, 0]
        + np.sin(3 * X[:, 0])
        - 1.5 * X[:, 1]
        - np.cos(2 * X[:, 1])
        + 1.0 * np.sin(2 * X[:, 2])
        + 0.1 * rng.randn(n)
    )
    return X, y


def test_monotone_constraints_enforced():
    X, y = _make_monotone_data()
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "monotone_constraints": [1, -1, 0], "min_data_in_leaf": 10},
        train, num_boost_round=30,
    )
    assert _is_monotone(bst, 0, +1)
    assert _is_monotone(bst, 1, -1)
    # the unconstrained model should NOT be monotone on this data (sanity)
    bst_free = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "min_data_in_leaf": 10},
        lgb.Dataset(X, label=y), num_boost_round=30,
    )
    assert not (_is_monotone(bst_free, 0, +1) and _is_monotone(bst_free, 1, -1))


def test_monotone_constraints_still_learn():
    X, y = _make_monotone_data(seed=1)
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "monotone_constraints": [1, -1, 0], "min_data_in_leaf": 10},
        train, num_boost_round=40,
    )
    pred = bst.predict(X)
    r2 = 1 - np.mean((pred - y) ** 2) / np.var(y)
    assert r2 > 0.8, r2


def _paths_features(tree):
    """Set of feature-index frozensets, one per root->leaf path."""
    paths = []

    def walk(node, feats):
        if node < 0:
            paths.append(frozenset(feats))
            return
        f = int(tree.split_feature[node])
        walk(int(tree.left_child[node]), feats | {f})
        walk(int(tree.right_child[node]), feats | {f})

    if tree.num_leaves > 1:
        walk(0, set())
    return paths


def test_interaction_constraints_respected():
    rng = np.random.RandomState(2)
    n = 4000
    X = rng.randn(n, 4)
    y = X[:, 0] * X[:, 1] + X[:, 2] * X[:, 3] + 0.1 * rng.randn(n)
    train = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "interaction_constraints": [[0, 1], [2, 3]], "min_data_in_leaf": 10},
        train, num_boost_round=20,
    )
    allowed = [frozenset({0, 1}), frozenset({2, 3})]
    for t in bst._gbdt.models:
        for path in _paths_features(t):
            assert any(path <= a for a in allowed), path


def test_interaction_constraints_string_form():
    rng = np.random.RandomState(3)
    X = rng.randn(2000, 3)
    y = X[:, 0] + X[:, 1] + X[:, 2] + 0.1 * rng.randn(2000)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "interaction_constraints": "[0],[1,2]"},
        lgb.Dataset(X, label=y), num_boost_round=10,
    )
    allowed = [frozenset({0}), frozenset({1, 2})]
    for t in bst._gbdt.models:
        for path in _paths_features(t):
            assert any(path <= a for a in allowed), path


def test_extra_trees_trains_and_differs():
    rng = np.random.RandomState(4)
    X = rng.randn(3000, 8)
    y = X @ rng.randn(8) + 0.2 * rng.randn(3000)
    p = {"objective": "regression", "num_leaves": 31, "verbosity": -1}
    bst = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=15)
    bst_x = lgb.train(dict(p, extra_trees=True), lgb.Dataset(X, label=y), num_boost_round=15)
    pred, pred_x = bst.predict(X), bst_x.predict(X)
    assert not np.allclose(pred, pred_x)  # random thresholds change the model
    r2 = 1 - np.mean((pred_x - y) ** 2) / np.var(y)
    assert r2 > 0.7, r2


def test_feature_fraction_bynode():
    rng = np.random.RandomState(5)
    X = rng.randn(3000, 10)
    y = X @ rng.randn(10) + 0.2 * rng.randn(3000)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "feature_fraction_bynode": 0.5},
        lgb.Dataset(X, label=y), num_boost_round=15,
    )
    r2 = 1 - np.mean((bst.predict(X) - y) ** 2) / np.var(y)
    assert r2 > 0.6, r2


def test_monotone_intermediate_monotone_and_looser_than_basic():
    """VERDICT r2 item 9: intermediate bounds use opposite-subtree output
    extremes instead of compounded midpoints (reference:
    IntermediateLeafConstraints).  Fixture where basic over-constrains: the
    left region's high plateau (8) exceeds basic's midpoint fence (~7) but
    not the right subtree's minimum (10)."""
    rng = np.random.RandomState(0)
    n = 4000
    x0, x1 = rng.randn(n), rng.randn(n)
    y = np.where(x0 > 0, 10.0, np.where(x1 > 0, 8.0, 0.0)) + 0.01 * rng.randn(n)
    X = np.c_[x0, x1]

    def fit(method):
        ds = lgb.Dataset(X, label=y)
        return lgb.train(
            {"objective": "regression", "num_leaves": 8, "verbosity": -1,
             "learning_rate": 1.0, "tree_growth_mode": "strict",
             "monotone_constraints": [1, 0],
             "monotone_constraints_method": method},
            ds, 1)

    basic, inter = fit("basic"), fit("intermediate")

    # property: predictions non-decreasing in the constrained feature
    xs = np.linspace(-3, 3, 201)
    for bst in (basic, inter):
        for x1v in (-1.5, 0.0, 1.5):
            grid = np.c_[xs, np.full_like(xs, x1v)]
            p = bst.predict(grid)
            assert np.all(np.diff(p) >= -1e-6)

    # intermediate must fit the fixture strictly better than basic
    mse_b = float(np.mean((basic.predict(X) - y) ** 2))
    mse_i = float(np.mean((inter.predict(X) - y) ** 2))
    assert mse_i < mse_b * 0.8, (mse_i, mse_b)
    # and its total split gain (summed over ALL nodes) is at least basic's
    def total_gain(nd):
        if "split_feature" not in nd:
            return 0.0
        return (nd.get("split_gain", 0.0)
                + total_gain(nd["left_child"]) + total_gain(nd["right_child"]))

    gain_b = sum(total_gain(t["tree_structure"])
                 for t in basic.dump_model()["tree_info"])
    gain_i = sum(total_gain(t["tree_structure"])
                 for t in inter.dump_model()["tree_info"])
    assert gain_i > gain_b


def test_monotone_intermediate_rounds_grower():
    """VERDICT r3 item 4: intermediate bounds on the round-batched TPU
    grower.  Same fixture as the strict test; round-batched splits clip
    sequentially in admission order (treegrow_fast.py round_body), so the
    pairwise monotone invariant must hold exactly as it does for strict."""
    rng = np.random.RandomState(0)
    n = 4000
    x0, x1 = rng.randn(n), rng.randn(n)
    y = np.where(x0 > 0, 10.0, np.where(x1 > 0, 8.0, 0.0)) + 0.01 * rng.randn(n)
    X = np.c_[x0, x1]

    def fit(method):
        ds = lgb.Dataset(X, label=y)
        return lgb.train(
            {"objective": "regression", "num_leaves": 8, "verbosity": -1,
             "learning_rate": 1.0, "tree_growth_mode": "rounds",
             "monotone_constraints": [1, 0],
             "monotone_constraints_method": method},
            ds, 1)

    basic, inter = fit("basic"), fit("intermediate")

    xs = np.linspace(-3, 3, 201)
    for bst in (basic, inter):
        for x1v in (-1.5, 0.0, 1.5):
            grid = np.c_[xs, np.full_like(xs, x1v)]
            p = bst.predict(grid)
            assert np.all(np.diff(p) >= -1e-6)

    # intermediate must fit the fixture strictly better than basic
    mse_b = float(np.mean((basic.predict(X) - y) ** 2))
    mse_i = float(np.mean((inter.predict(X) - y) ** 2))
    assert mse_i < mse_b * 0.8, (mse_i, mse_b)


def test_monotone_intermediate_rounds_multi_split_stress():
    """Multiple same-round splits on BOTH sides of monotone nodes: the
    within-round sequential clip must keep predictions monotone in both
    constrained features across a deep multi-iteration model."""
    X, y = _make_monotone_data(n=3000, seed=3)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "learning_rate": 0.2, "tree_growth_mode": "rounds",
         "min_data_in_leaf": 5,
         "monotone_constraints": [1, -1, 0],
         "monotone_constraints_method": "intermediate"},
        ds, 20)
    assert _is_monotone(bst, 0, +1)
    assert _is_monotone(bst, 1, -1)
    # the unconstrained feature still moves predictions (sanity)
    rng = np.random.RandomState(1)
    probe = rng.randn(50, 3)
    alt = probe.copy()
    alt[:, 2] += 1.0
    assert not np.allclose(bst.predict(probe), bst.predict(alt))


@pytest.mark.parametrize("learner", ["feature", "voting"])
def test_monotone_intermediate_parallel_learners(learner):
    """VERDICT r4 item 6 (lift): intermediate bounds on the feature- and
    voting-parallel learners (8-device CPU mesh).  The re-evaluate-all
    path vmaps the per-leaf search, batching the shard collectives;
    node_mono records split directions because feature mode shards the
    constraint vector.  Monotonicity must hold AND intermediate must beat
    basic on the fixture where basic's midpoint fence over-constrains."""
    rng = np.random.RandomState(0)
    n = 4000
    x0, x1 = rng.randn(n), rng.randn(n)
    y = np.where(x0 > 0, 10.0, np.where(x1 > 0, 8.0, 0.0)) + 0.01 * rng.randn(n)
    X = np.c_[x0, x1]

    def fit(method):
        ds = lgb.Dataset(X, label=y)
        return lgb.train(
            {"objective": "regression", "num_leaves": 8, "verbosity": -1,
             "learning_rate": 1.0, "tree_learner": learner,
             "top_k": 2,
             "monotone_constraints": [1, 0],
             "monotone_constraints_method": method},
            ds, 1)

    basic, inter = fit("basic"), fit("intermediate")
    xs = np.linspace(-3, 3, 201)
    for bst in (basic, inter):
        for x1v in (-1.5, 0.0, 1.5):
            grid = np.c_[xs, np.full_like(xs, x1v)]
            p = bst.predict(grid)
            assert np.all(np.diff(p) >= -1e-6)
    mse_b = float(np.mean((basic.predict(X) - y) ** 2))
    mse_i = float(np.mean((inter.predict(X) - y) ** 2))
    assert mse_i < mse_b * 0.8, (mse_i, mse_b)
