"""Categorical split tests.

Mirrors the reference's categorical coverage in
tests/python_package_test/test_engine.py (categorical round-trips, one-hot vs
many-vs-many) plus a brute-force oracle for the sorted-subset search
(reference: feature_histogram.hpp -> FindBestThresholdCategoricalInner).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _make_cat_regression(n=4000, n_cat=12, seed=0):
    rng = np.random.RandomState(seed)
    cat = rng.randint(0, n_cat, n).astype(np.float64)
    effect = rng.randn(n_cat) * 2.0
    X = np.column_stack([cat, rng.randn(n), rng.randn(n)])
    y = effect[cat.astype(int)] + 0.1 * rng.randn(n)
    return X, y


def test_categorical_regression_learns_signal():
    X, y = _make_cat_regression()
    train = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "min_data_in_leaf": 5,
         "verbosity": -1, "learning_rate": 0.2},
        train, num_boost_round=30,
    )
    pred = bst.predict(X)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.3, rmse
    # the ensemble must actually contain categorical (bitset) splits
    assert any(t.num_cat > 0 for t in bst._gbdt.models)


def test_categorical_save_load_bit_exact():
    X, y = _make_cat_regression(seed=1)
    train = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        train, num_boost_round=8,
    )
    pred = bst.predict(X)
    bst2 = lgb.Booster.model_from_string(bst.model_to_string())
    np.testing.assert_array_equal(pred, bst2.predict(X))


def test_categorical_unseen_category_goes_right():
    X, y = _make_cat_regression(seed=2)
    train = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        train, num_boost_round=5,
    )
    X_unseen = X[:16].copy()
    X_unseen[:, 0] = 999.0  # never-seen category
    out = bst.predict(X_unseen)
    assert np.all(np.isfinite(out))
    # NaN categorical behaves like not-in-bitset (same traversal as unseen)
    X_nan = X[:16].copy()
    X_nan[:, 0] = np.nan
    out_nan = bst.predict(X_nan)
    assert np.all(np.isfinite(out_nan))


def test_categorical_onehot_small_cardinality_oracle():
    """With <= max_cat_to_onehot categories the split must be one-vs-rest and
    match a brute-force oracle on the root split."""
    rng = np.random.RandomState(3)
    n = 2000
    cat = rng.randint(0, 3, n).astype(np.float64)
    y = np.where(cat == 1, 5.0, 0.0) + 0.01 * rng.randn(n)
    X = cat[:, None]
    train = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 2, "min_data_in_leaf": 1,
         "verbosity": -1, "learning_rate": 1.0, "max_cat_to_onehot": 4,
         "lambda_l2": 0.0, "cat_l2": 0.0, "cat_smooth": 0.0,
         "boost_from_average": False},
        train, num_boost_round=1,
    )
    tree = bst._gbdt.models[0]
    assert tree.num_cat == 1
    # the isolated side (one-hot left subset) must be exactly category 1
    left = [c for c in range(3) if tree.cat_decision_left(0, float(c))]
    assert left == [1], left


def test_categorical_many_vs_many_oracle():
    """Root split vs brute-force over all sorted-prefix subsets
    (the reference's search space: prefixes of the g/(h+cat_smooth) order)."""
    rng = np.random.RandomState(4)
    n = 3000
    k = 8
    cat = rng.randint(0, k, n).astype(np.float64)
    effect = np.array([3.0, -2.0, 1.0, 0.5, -1.0, 2.0, -3.0, 0.0])
    y = effect[cat.astype(int)] + 0.01 * rng.randn(n)
    X = cat[:, None]
    cat_smooth = 10.0
    params = {
        "objective": "regression", "num_leaves": 2, "min_data_in_leaf": 1,
        "verbosity": -1, "learning_rate": 1.0, "max_cat_to_onehot": 1,
        "lambda_l2": 0.0, "cat_l2": 0.0, "cat_smooth": cat_smooth,
        "min_sum_hessian_in_leaf": 0.0, "boost_from_average": False,
    }
    train = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train(params, train, num_boost_round=1)
    tree = bst._gbdt.models[0]
    assert tree.num_cat == 1
    chosen_left = frozenset(c for c in range(k) if tree.cat_decision_left(0, float(c)))

    # oracle: L2 objective => grad = pred - y = -y at score 0, hess = 1
    g = np.array([-(y[cat == c]).sum() for c in range(k)])
    h = np.array([float((cat == c).sum()) for c in range(k)])
    ratio = g / (h + cat_smooth)
    best_gain, best_subset = -1.0, None
    for order in (np.argsort(ratio), np.argsort(-ratio)):
        for plen in range(1, k):
            left = order[:plen]
            lg, lh = g[left].sum(), h[left].sum()
            rg, rh = g.sum() - lg, h.sum() - lh
            gain = lg * lg / lh + rg * rg / rh - g.sum() ** 2 / h.sum()
            if gain > best_gain:
                best_gain, best_subset = gain, frozenset(int(c) for c in left)
    # the chosen subset (or its complement — sides are symmetric) must match
    assert chosen_left in (best_subset, frozenset(range(k)) - best_subset)


def test_categorical_multiclass():
    rng = np.random.RandomState(5)
    n = 3000
    cat = rng.randint(0, 6, n).astype(np.float64)
    y = cat.astype(int) % 3
    X = np.column_stack([cat, rng.randn(n)])
    train = lgb.Dataset(X, label=y.astype(np.float64), categorical_feature=[0])
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
         "min_data_in_leaf": 5, "verbosity": -1},
        train, num_boost_round=10,
    )
    pred = bst.predict(X)
    acc = float((pred.argmax(axis=1) == y).mean())
    assert acc > 0.95, acc


def test_categorical_shap_sums_to_prediction():
    X, y = _make_cat_regression(n=500, seed=6)
    train = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1},
        train, num_boost_round=4,
    )
    contrib = bst.predict(X[:32], pred_contrib=True)
    pred = bst.predict(X[:32])
    np.testing.assert_allclose(contrib.sum(axis=1), pred, rtol=1e-5, atol=1e-5)
