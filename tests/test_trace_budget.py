"""Trace-size budget: the growers' round-body jaxpr must stay small.

The r5 warmup regression (~137 s -> ~240 s fused-step compile on the
remote toolchain, docs/NEXT.md lever 4) motivated making trace size an
artifact metric (bench.py records trace_eqns per run); this test is the
tier-1 half — a generous ceiling that catches structural trace bloat
(an accidentally unrolled loop, a per-leaf-tile op explosion) at PR time
without being brittle to jax version drift.  Measured round-7 baselines:
grow_tree_fast tile8 ~1.74k eqns, tile16 ~2.23k; fused windowed round
tile8 ~2.13k (benchmarks/probe_trace_ops.py)."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks.probe_trace_ops import (count_eqns, fast_grower_eqns,  # noqa: E402
                                        windowed_round_eqns)


def test_fast_grower_trace_budget():
    assert fast_grower_eqns(leaf_tile=8) < 2300
    assert fast_grower_eqns(leaf_tile=16) < 3000


def test_windowed_fused_round_trace_budget():
    assert windowed_round_eqns(leaf_tile=8) < 2800


def test_count_eqns_descends_subjaxprs():
    import jax
    import jax.numpy as jnp

    def f(x):
        return jax.lax.fori_loop(0, 4, lambda i, a: a * 2 + i, x)

    j = jax.make_jaxpr(f)(jnp.float32(1.0))
    assert count_eqns(j.jaxpr) > len(j.jaxpr.eqns)
