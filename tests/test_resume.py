"""Failure-recovery round trip (SURVEY §6.3): snapshot + restart from
init_model must reproduce uninterrupted training (the reference's recovery
story is exactly snapshot_freq + task=train input_model=...).

Round 8 additions run in TIER-1 (unmarked): a tiny 2+2 round trip, the
atomic/trailered snapshot format, torn-snapshot fallback, and the
crash-injection scenarios (host crash / snapshot-write crash at round k
via LGBMTPU_FAULT in a subprocess, then resume and match the
uninterrupted run)."""

import os
import subprocess
import sys

import pytest
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import CorruptModelError
from lightgbm_tpu.utils import checkpoint


def _data(n=200, f=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "learning_rate": 0.2}


# ---------------------------------------------------------------------------
# tier-1: fast snapshot/resume round trip + checkpoint format
# ---------------------------------------------------------------------------

def test_fast_snapshot_resume_roundtrip(tmp_path):
    """2+2 rounds through a snapshot == 4 uninterrupted rounds — the
    smallest possible recovery equivalence, cheap enough for tier-1."""
    X, y = _data()
    full = lgb.train(PARAMS, lgb.Dataset(X, label=y), 4)

    out = str(tmp_path / "model.txt")
    lgb.train({**PARAMS, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 2)
    snap = f"{out}.snapshot_iter_2"
    assert os.path.exists(snap)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2, init_model=snap)

    assert resumed.num_trees() == 4
    np.testing.assert_allclose(
        resumed.predict(X), full.predict(X), rtol=1e-5, atol=1e-6)


def test_snapshot_carries_verifiable_trailer(tmp_path):
    X, y = _data(seed=1)
    out = str(tmp_path / "m.txt")
    lgb.train({**PARAMS, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 2)
    snap = f"{out}.snapshot_iter_2"
    assert checkpoint.verify_file(snap) is True
    # the trailer is stripped on load: the snapshot parses into a booster
    assert lgb.Booster(model_file=snap).num_trees() == 2
    text = open(snap).read()
    # payload corruption under an intact trailer: digest mismatch
    corrupt = str(tmp_path / "corrupt.txt.snapshot_iter_9")
    open(corrupt, "w").write(text.replace("num_leaves", "num_leavez", 1))
    assert checkpoint.verify_file(corrupt) is False
    with pytest.raises(CorruptModelError):
        lgb.Booster(model_file=corrupt)
    # plain truncation chops the trailer off — for a snapshot-named file
    # that is equally torn (snapshots are always written with a trailer)
    torn = str(tmp_path / "torn.txt.snapshot_iter_9")
    open(torn, "w").write(text[: int(len(text) * 0.7)])
    with pytest.raises(CorruptModelError):
        lgb.Booster(model_file=torn)


def test_trailerless_model_files_still_load(tmp_path):
    """Plain save_model output has no trailer (legacy format) and must
    keep loading unchanged."""
    X, y = _data(seed=2)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2)
    p = str(tmp_path / "plain.txt")
    bst.save_model(p)
    assert checkpoint.verify_file(p) is None
    assert lgb.Booster(model_file=p).num_trees() == 2


def test_resume_falls_back_to_newest_valid_snapshot(tmp_path):
    """A torn newest snapshot must not kill the resume: engine.train
    falls back to the newest snapshot whose trailer verifies."""
    X, y = _data(seed=3)
    out = str(tmp_path / "m.txt")
    lgb.train({**PARAMS, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 4)
    snap2, snap4 = (f"{out}.snapshot_iter_{k}" for k in (2, 4))
    assert checkpoint.verify_file(snap4) is True
    # tear the newest snapshot
    text = open(snap4).read()
    open(snap4, "w").write(text[: len(text) // 2])
    assert checkpoint.verify_file(snap4) is False
    assert checkpoint.latest_valid_snapshot(snap4) == (2, snap2)

    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2,
                        init_model=snap4)
    # fell back to iter 2 and trained 2 more: 4 trees
    assert resumed.num_trees() == 4
    ref = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2, init_model=snap2)
    np.testing.assert_allclose(resumed.predict(X), ref.predict(X),
                               rtol=1e-6, atol=1e-7)


def test_resume_with_no_valid_fallback_raises(tmp_path):
    X, y = _data(seed=4)
    out = str(tmp_path / "m.txt")
    lgb.train({**PARAMS, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 2)
    snap = f"{out}.snapshot_iter_2"
    text = open(snap).read()
    open(snap, "w").write(text[: len(text) // 2])
    with pytest.raises(CorruptModelError):
        lgb.train(PARAMS, lgb.Dataset(X, label=y), 2, init_model=snap)


def test_atomic_write_never_tears_on_exception(tmp_path):
    """atomic_write_text: a failure mid-write leaves the previous file
    byte-identical and no temp debris behind."""
    p = str(tmp_path / "f.txt")
    checkpoint.atomic_write_text(p, "generation one\n")

    class Boom(RuntimeError):
        pass

    real_replace = os.replace

    def exploding_replace(src, dst):
        raise Boom("crash between temp write and rename")

    os.replace = exploding_replace
    try:
        with pytest.raises(Boom):
            checkpoint.atomic_write_text(p, "generation two\n")
    finally:
        os.replace = real_replace
    assert open(p).read() == "generation one\n"
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []


# ---------------------------------------------------------------------------
# tier-1: crash injection in a subprocess, resume in-process
# ---------------------------------------------------------------------------

_CRASH_SCRIPT = """
import os, sys
import numpy as np
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb

rng = np.random.RandomState(0)
X = rng.randn(200, 4)
y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)
lgb.train({{"objective": "binary", "num_leaves": 7, "verbosity": -1,
           "learning_rate": 0.2, "snapshot_freq": 2,
           "output_model": {out!r}}},
          lgb.Dataset(X, label=y), 6)
print("COMPLETED_WITHOUT_FAULT", flush=True)
"""


def _run_crashing_train(tmp_path, fault: str):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "m.txt")
    env = dict(os.environ)
    env["LGBMTPU_FAULT"] = fault
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTEST_CURRENT_TEST", None)
    r = subprocess.run(
        [sys.executable, "-c", _CRASH_SCRIPT.format(repo=repo, out=out)],
        env=env, capture_output=True, text=True, timeout=300)
    return out, r


def test_host_crash_at_round_k_resumes_and_matches(tmp_path):
    """The acceptance scenario: kill the host at round 4 (after snapshot
    iter 2), resume from the newest valid snapshot, and reproduce the
    uninterrupted 6-round model bit-for-bit in predictions."""
    from lightgbm_tpu.utils.faults import CRASH_EXIT_CODE

    out, r = _run_crashing_train(tmp_path, "host_crash:4")
    assert r.returncode == CRASH_EXIT_CODE, (r.stdout, r.stderr)
    assert "COMPLETED_WITHOUT_FAULT" not in r.stdout

    found = checkpoint.latest_valid_snapshot(out)
    assert found is not None
    it, snap = found
    assert it == 2  # crash at the start of round 4: snapshots 1..2 survive

    X, y = _data()  # same data/seed as the crashed run
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6 - it,
                        init_model=snap)
    full = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6)
    assert resumed.num_trees() == 6
    np.testing.assert_allclose(
        resumed.predict(X), full.predict(X), rtol=1e-5, atol=1e-6)


def test_snapshot_write_crash_leaves_no_torn_snapshot(tmp_path):
    """Kill the process MID-SNAPSHOT-WRITE (iteration 4's snapshot).  The
    old direct-write code left a torn snapshot_iter_4 that resume loaded;
    the atomic writer must leave either no iter-4 snapshot or a fully
    valid one — and resume must work from the newest valid snapshot."""
    from lightgbm_tpu.utils.faults import CRASH_EXIT_CODE

    out, r = _run_crashing_train(tmp_path, "snapshot_write:4")
    assert r.returncode == CRASH_EXIT_CODE, (r.stdout, r.stderr)

    for it, snap in checkpoint.snapshot_family(out):
        assert checkpoint.verify_file(snap) is True, (
            f"torn snapshot survived the crash: {snap}")
    found = checkpoint.latest_valid_snapshot(out)
    assert found is not None and found[0] == 2
    X, y = _data()
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2,
                        init_model=found[1])
    assert resumed.num_trees() == 4


# ---------------------------------------------------------------------------
# slow: the original wider round trips
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_snapshot_resume_matches_uninterrupted(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "learning_rate": 0.2}

    # uninterrupted: 8 rounds
    d = lgb.Dataset(X, label=y)
    full = lgb.train(params, d, num_boost_round=8)

    # interrupted: 4 rounds with a snapshot, then resume for 4 more
    out = str(tmp_path / "model.txt")
    d2 = lgb.Dataset(X, label=y)
    lgb.train({**params, "snapshot_freq": 4, "output_model": out},
              d2, num_boost_round=4)
    snap = f"{out}.snapshot_iter_4"
    d3 = lgb.Dataset(X, label=y)
    resumed = lgb.train(params, d3, num_boost_round=4, init_model=snap)

    assert resumed.num_trees() == 8
    np.testing.assert_allclose(
        resumed.predict(X), full.predict(X), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_cli_resume_via_input_model(tmp_path):
    """CLI restart: task=train input_model=snapshot continues training."""
    rng = np.random.RandomState(1)
    X = rng.randn(300, 3)
    y = (X[:, 0] > 0).astype(float)
    data = str(tmp_path / "train.csv")
    np.savetxt(data, np.column_stack([y, X]), delimiter=",")
    m1 = str(tmp_path / "m1.txt")
    m2 = str(tmp_path / "m2.txt")
    env_args = ["task=train", f"data={data}", "objective=binary",
                "label_column=0", "verbosity=-1", "num_leaves=7"]
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", *env_args,
         "num_iterations=3", f"output_model={m1}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", *env_args,
         "num_iterations=2", f"input_model={m1}", f"output_model={m2}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    bst = lgb.Booster(model_file=m2)
    assert bst.num_trees() == 5


def test_fallback_never_resumes_from_a_newer_stale_snapshot(tmp_path):
    """A stale NEWER snapshot (left by a previous longer run on the same
    prefix) must not win the fallback scan: resuming 'forward' of the
    requested iteration would silently produce a model with the wrong
    trees.  The scan is bounded to strictly OLDER siblings."""
    X, y = _data(seed=5)
    out = str(tmp_path / "m.txt")
    # previous, longer run: leaves snapshots 2..6
    lgb.train({**PARAMS, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 6)
    snap2 = f"{out}.snapshot_iter_2"
    snap4 = f"{out}.snapshot_iter_4"
    assert checkpoint.verify_file(f"{out}.snapshot_iter_6") is True
    # current run's newest usable snapshot is iter 4 — tear it
    text = open(snap4).read()
    open(snap4, "w").write(text[: len(text) // 2])

    assert checkpoint.latest_valid_snapshot(snap4, below_iter=4) == (2, snap2)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2,
                        init_model=snap4)
    ref = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2, init_model=snap2)
    # fell back to iter 2 (2 + 2 trees), NOT forward to iter 6 (6 + 2)
    assert resumed.num_trees() == 4
    np.testing.assert_allclose(resumed.predict(X), ref.predict(X),
                               rtol=1e-6, atol=1e-7)


def test_pre_trailer_snapshot_loads_as_last_resort(tmp_path):
    """A snapshot written by the pre-trailer release (intact, just no
    trailer) must still be resumable when no verified fallback exists —
    rejecting the whole family would throw away real progress."""
    X, y = _data(seed=6)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2)
    legacy = str(tmp_path / "old.txt.snapshot_iter_2")
    # simulate the old direct-write path: raw model text, no trailer
    open(legacy, "w").write(bst.model_to_string())

    # direct Booster load stays strict (cannot vouch for the file)...
    with pytest.raises(CorruptModelError):
        lgb.Booster(model_file=legacy)
    # ...but engine resume accepts it as a loud last resort
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2,
                        init_model=legacy)
    assert resumed.num_trees() == 4


def test_resumed_run_snapshots_use_global_iteration_numbers(tmp_path):
    """A resumed run's snapshots continue the GLOBAL iteration numbering:
    round 1 of a resume-from-iter-4 run writes snapshot_iter_6, never an
    overwrite of snapshot_iter_2 with a 6-tree model (which would poison
    the fallback scan's iteration arithmetic)."""
    X, y = _data(seed=7)
    out = str(tmp_path / "m.txt")
    lgb.train({**PARAMS, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 4)  # writes snapshots 2 and 4
    resumed = lgb.train(
        {**PARAMS, "snapshot_freq": 2, "output_model": out},
        lgb.Dataset(X, label=y), 2, init_model=f"{out}.snapshot_iter_4")
    assert resumed.num_trees() == 6
    # old snapshots untouched, new one numbered globally
    assert lgb.Booster(model_file=f"{out}.snapshot_iter_2").num_trees() == 2
    assert lgb.Booster(model_file=f"{out}.snapshot_iter_6").num_trees() == 6
    assert checkpoint.latest_valid_snapshot(out) == (
        6, f"{out}.snapshot_iter_6")


def test_bitrotted_snapshot_falls_back_not_crashes(tmp_path):
    """Binary garbage in the newest snapshot (invalid UTF-8) is 'torn',
    not an uncaught UnicodeDecodeError: resume falls back to the valid
    older sibling."""
    X, y = _data(seed=8)
    out = str(tmp_path / "m.txt")
    lgb.train({**PARAMS, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 4)
    snap4 = f"{out}.snapshot_iter_4"
    open(snap4, "wb").write(b"\xff\xfe\x00garbage" * 100)
    assert checkpoint.verify_file(snap4) is False
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2,
                        init_model=snap4)
    assert resumed.num_trees() == 4  # fell back to iter 2, +2 rounds


# ---------------------------------------------------------------------------
# tier-1: resume="auto" — recovery without naming a snapshot (round 9)
# ---------------------------------------------------------------------------

def test_auto_resume_picks_latest_valid_and_trains_remainder(tmp_path):
    """Re-running the ORIGINAL command with resume=auto after a crash at
    round 4 continues from snapshot_iter_4 and trains only the remaining
    2 rounds — equivalent to the uninterrupted 6-round run."""
    X, y = _data(seed=11)
    full = lgb.train(PARAMS, lgb.Dataset(X, label=y), 6)

    out = str(tmp_path / "m.txt")
    run_params = {**PARAMS, "snapshot_freq": 2, "output_model": out}
    lgb.train(run_params, lgb.Dataset(X, label=y), 4)  # "crashed" at 4
    resumed = lgb.train(run_params, lgb.Dataset(X, label=y), 6,
                        resume="auto")
    assert resumed.num_trees() == 6
    np.testing.assert_allclose(
        resumed.predict(X), full.predict(X), rtol=1e-5, atol=1e-6)

    # target already reached: zero further rounds, model unchanged
    again = lgb.train(run_params, lgb.Dataset(X, label=y), 4, resume="auto")
    assert again.num_trees() == 4


def test_auto_resume_skips_torn_newest_snapshot(tmp_path):
    X, y = _data(seed=12)
    out = str(tmp_path / "m.txt")
    run_params = {**PARAMS, "snapshot_freq": 2, "output_model": out}
    lgb.train(run_params, lgb.Dataset(X, label=y), 4)
    snap4 = f"{out}.snapshot_iter_4"
    text = open(snap4).read()
    open(snap4, "w").write(text[: int(len(text) * 0.6)])  # torn
    resumed = lgb.train(run_params, lgb.Dataset(X, label=y), 6,
                        resume="auto")
    # fell back to the valid iter-2 snapshot, trained 4 more
    assert resumed.num_trees() == 6


def test_auto_resume_fresh_start_and_param_form(tmp_path):
    """No snapshots yet: resume=auto starts fresh; the config-param form
    (resume=auto in params, the CLI spelling) behaves identically."""
    X, y = _data(seed=13)
    out = str(tmp_path / "m.txt")
    run_params = {**PARAMS, "snapshot_freq": 2, "output_model": out,
                  "resume": "auto"}
    first = lgb.train(run_params, lgb.Dataset(X, label=y), 4)
    assert first.num_trees() == 4
    resumed = lgb.train(run_params, lgb.Dataset(X, label=y), 6)
    assert resumed.num_trees() == 6


def test_auto_resume_rejects_unknown_mode(tmp_path):
    X, y = _data(seed=14)
    with pytest.raises(lgb.basic.LightGBMError):
        lgb.train(PARAMS, lgb.Dataset(X, label=y), 2, resume="latest")


# ---------------------------------------------------------------------------
# tier-1: snapshot retention (snapshot_keep=, round 13)
# ---------------------------------------------------------------------------

def test_snapshot_keep_prunes_oldest_after_each_write(tmp_path):
    """snapshot_keep=2 with snapshot_freq=1 leaves exactly the newest two
    snapshots on disk after training (default 0 keeps all — pinned by
    every other test in this file)."""
    X, y = _data(seed=15)
    out = str(tmp_path / "m.txt")
    lgb.train({**PARAMS, "snapshot_freq": 1, "snapshot_keep": 2,
               "output_model": out}, lgb.Dataset(X, label=y), 5)
    assert [it for it, _ in checkpoint.snapshot_family(out)] == [5, 4]
    # resume still works from what retention kept
    resumed = lgb.train({**PARAMS, "snapshot_freq": 1, "snapshot_keep": 2,
                         "output_model": out},
                        lgb.Dataset(X, label=y), 6, resume="auto")
    assert resumed.num_trees() == 6


def test_prune_never_removes_newest_valid_snapshot(tmp_path):
    """A family whose newest entries are all torn keeps its last GOOD
    snapshot whatever the keep bound — retention must not be able to
    throw away the only resumable state."""
    X, y = _data(seed=16)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), 2)
    out = str(tmp_path / "m.txt")
    for it in (1, 2, 3, 4):
        checkpoint.save_snapshot(f"{out}.snapshot_iter_{it}",
                                 bst.model_to_string(), it)
    for it in (3, 4):  # newest two torn
        p = f"{out}.snapshot_iter_{it}"
        t = open(p).read()
        open(p, "w").write(t[: len(t) // 2])
    pruned = checkpoint.prune_snapshots(out, keep=2)
    # 1 pruned; 2 survives as the newest VALID despite being beyond keep
    assert [it for it, _ in pruned] == [1]
    assert checkpoint.latest_valid_snapshot(out) == (
        2, f"{out}.snapshot_iter_2")


def test_linear_tree_resume_replays_linear_terms(tmp_path):
    """Resume of a linear_tree model must replay the per-leaf LINEAR
    terms, not just leaf_value — a constant-only replay rebuilds a wrong
    score base and every post-resume tree diverges."""
    rng = np.random.RandomState(17)
    X = rng.randn(400, 3)
    y = X[:, 0] * np.where(X[:, 1] > 0, 2.0, -1.0) + 0.05 * rng.randn(400)
    params = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
              "linear_tree": True, "min_data_in_leaf": 10}
    full = lgb.train(params, lgb.Dataset(X, label=y), 4)

    out = str(tmp_path / "lin.txt")
    lgb.train({**params, "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), 2)
    resumed = lgb.train(params, lgb.Dataset(X, label=y), 2,
                        init_model=f"{out}.snapshot_iter_2")
    assert resumed.num_trees() == 4
    np.testing.assert_allclose(resumed.predict(X), full.predict(X),
                               rtol=1e-4, atol=1e-5)
