"""Failure-recovery round trip (SURVEY §6.3): snapshot + restart from
init_model must reproduce uninterrupted training (the reference's recovery
story is exactly snapshot_freq + task=train input_model=...)."""

import pytest
import numpy as np

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def test_snapshot_resume_matches_uninterrupted(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "learning_rate": 0.2}

    # uninterrupted: 8 rounds
    d = lgb.Dataset(X, label=y)
    full = lgb.train(params, d, num_boost_round=8)

    # interrupted: 4 rounds with a snapshot, then resume for 4 more
    out = str(tmp_path / "model.txt")
    d2 = lgb.Dataset(X, label=y)
    lgb.train({**params, "snapshot_freq": 4, "output_model": out},
              d2, num_boost_round=4)
    snap = f"{out}.snapshot_iter_4"
    d3 = lgb.Dataset(X, label=y)
    resumed = lgb.train(params, d3, num_boost_round=4, init_model=snap)

    assert resumed.num_trees() == 8
    np.testing.assert_allclose(
        resumed.predict(X), full.predict(X), rtol=1e-5, atol=1e-6)


def test_cli_resume_via_input_model(tmp_path):
    """CLI restart: task=train input_model=snapshot continues training."""
    import subprocess
    import sys

    rng = np.random.RandomState(1)
    X = rng.randn(300, 3)
    y = (X[:, 0] > 0).astype(float)
    data = str(tmp_path / "train.csv")
    np.savetxt(data, np.column_stack([y, X]), delimiter=",")
    m1 = str(tmp_path / "m1.txt")
    m2 = str(tmp_path / "m2.txt")
    env_args = ["task=train", f"data={data}", "objective=binary",
                "label_column=0", "verbosity=-1", "num_leaves=7"]
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", *env_args,
         "num_iterations=3", f"output_model={m1}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", *env_args,
         "num_iterations=2", f"input_model={m1}", f"output_model={m2}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    bst = lgb.Booster(model_file=m2)
    assert bst.num_trees() == 5
