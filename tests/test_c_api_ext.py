"""Extended C API surface tests (reference: include/LightGBM/c_api.h) —
CSC/Mats/sampled-column ingestion, field/name introspection, streaming with
metadata, serialized references + ByteBuffer, model surgery (merge/refit/
leaf get-set/shuffle), score introspection, file predict, and the global
configuration entries."""

import ctypes
import os

import numpy as np
import pytest
import scipy.sparse as sp

import lightgbm_tpu as lgb

from test_c_api import _build

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def lib():
    lib = ctypes.CDLL(_build())
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(rc, lib):
    assert rc == 0, lib.LGBM_GetLastError()


def _dense_handle(lib, X, y, params=b"max_bin=63"):
    h = ctypes.c_void_p()
    Xc = np.ascontiguousarray(X, np.float64)
    _check(lib.LGBM_DatasetCreateFromMat(
        Xc.ctypes.data_as(ctypes.c_void_p), 1, Xc.shape[0], Xc.shape[1], 1,
        params, None, ctypes.byref(h)), lib)
    yc = np.ascontiguousarray(y, np.float32)
    _check(lib.LGBM_DatasetSetField(
        h, b"label", yc.ctypes.data_as(ctypes.c_void_p), len(yc), 0), lib)
    return h


def _train(lib, ds_handle, iters=3, params=b"objective=binary num_leaves=7 verbosity=-1"):
    bh = ctypes.c_void_p()
    _check(lib.LGBM_BoosterCreate(ds_handle, params, ctypes.byref(bh)), lib)
    fin = ctypes.c_int()
    for _ in range(iters):
        _check(lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)), lib)
    return bh


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(7)
    X = rng.randn(400, 5)
    y = ((X @ rng.randn(5)) > 0).astype(np.float64)
    return X, y


def test_csc_dataset_and_predict(lib, data):
    X, y = data
    csc = sp.csc_matrix(X)
    h = ctypes.c_void_p()
    _check(lib.LGBM_DatasetCreateFromCSC(
        csc.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p), 2,
        csc.indices.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
        csc.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(csc.indptr)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(X.shape[0]), b"max_bin=63", None,
        ctypes.byref(h)), lib)
    yc = y.astype(np.float32)
    _check(lib.LGBM_DatasetSetField(
        h, b"label", yc.ctypes.data_as(ctypes.c_void_p), len(yc), 0), lib)
    bh = _train(lib, h)

    # CSC-trained model == dense-trained model
    dh = _dense_handle(lib, X, y)
    bh2 = _train(lib, dh)
    s1 = _model_string(lib, bh)
    s2 = _model_string(lib, bh2)
    assert s1 == s2

    # PredictForCSC == PredictForMat
    out = np.zeros(X.shape[0])
    n_out = ctypes.c_int64()
    _check(lib.LGBM_BoosterPredictForCSC(
        bh, csc.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p), 2,
        csc.indices.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
        csc.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(csc.indptr)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(X.shape[0]), 0, 0, -1, b"", ctypes.byref(n_out),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))), lib)
    ref = np.zeros(X.shape[0])
    Xc = np.ascontiguousarray(X, np.float64)
    _check(lib.LGBM_BoosterPredictForMat(
        bh, Xc.ctypes.data_as(ctypes.c_void_p), 1, X.shape[0],
        X.shape[1], 1, 0, 0, -1, b"", ctypes.byref(n_out),
        ref.ctypes.data_as(ctypes.POINTER(ctypes.c_double))), lib)
    np.testing.assert_allclose(out, ref, rtol=1e-12)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_BoosterFree(bh2)
    lib.LGBM_DatasetFree(h)
    lib.LGBM_DatasetFree(dh)


def _model_string(lib, bh):
    n = ctypes.c_int64()
    _check(lib.LGBM_BoosterSaveModelToString(
        bh, 0, -1, 0, 0, ctypes.byref(n), None), lib)
    buf = ctypes.create_string_buffer(n.value)
    _check(lib.LGBM_BoosterSaveModelToString(
        bh, 0, -1, 0, n.value, ctypes.byref(n), buf), lib)
    return buf.value


def test_mats_dataset_and_predict(lib, data):
    X, y = data
    halves = [np.ascontiguousarray(X[:200], np.float64),
              np.ascontiguousarray(X[200:], np.float64)]
    ptrs = (ctypes.c_void_p * 2)(*[h.ctypes.data for h in halves])
    nrows = (ctypes.c_int32 * 2)(200, 200)
    h = ctypes.c_void_p()
    _check(lib.LGBM_DatasetCreateFromMats(
        2, ptrs, 1, nrows, X.shape[1], 1, b"max_bin=63", None,
        ctypes.byref(h)), lib)
    yc = y.astype(np.float32)
    _check(lib.LGBM_DatasetSetField(
        h, b"label", yc.ctypes.data_as(ctypes.c_void_p), len(yc), 0), lib)
    bh = _train(lib, h)
    assert _model_string(lib, bh) == _model_string(
        lib, _train(lib, _dense_handle(lib, X, y)))

    out = np.zeros(X.shape[0])
    n_out = ctypes.c_int64()
    _check(lib.LGBM_BoosterPredictForMats(
        bh, ptrs, 1, 2, nrows, X.shape[1], 0, 0, -1, b"",
        ctypes.byref(n_out),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))), lib)
    assert n_out.value == X.shape[0]
    assert np.isfinite(out).all()
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_DatasetFree(h)


def test_get_field_and_names(lib, data):
    X, y = data
    h = _dense_handle(lib, X, y)
    w = np.linspace(0.5, 1.5, len(y)).astype(np.float32)
    _check(lib.LGBM_DatasetSetField(
        h, b"weight", w.ctypes.data_as(ctypes.c_void_p), len(w), 0), lib)

    out_len = ctypes.c_int()
    out_ptr = ctypes.c_void_p()
    out_type = ctypes.c_int()
    _check(lib.LGBM_DatasetGetField(
        h, b"weight", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type)), lib)
    assert out_type.value == 0 and out_len.value == len(w)
    got = np.frombuffer(
        (ctypes.c_float * out_len.value).from_address(out_ptr.value),
        np.float32)
    np.testing.assert_allclose(got, w, rtol=1e-6)

    # group sizes in -> cumulative boundaries out (reference convention)
    g = np.asarray([100, 150, 150], np.int32)
    _check(lib.LGBM_DatasetSetField(
        h, b"group", g.ctypes.data_as(ctypes.c_void_p), len(g), 2), lib)
    _check(lib.LGBM_DatasetGetField(
        h, b"group", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type)), lib)
    assert out_type.value == 2
    bounds = np.frombuffer(
        (ctypes.c_int32 * out_len.value).from_address(out_ptr.value),
        np.int32)
    np.testing.assert_array_equal(bounds, [0, 100, 250, 400])

    names = [b"alpha", b"beta", b"gamma", b"delta", b"eps"]
    arr = (ctypes.c_char_p * 5)(*names)
    _check(lib.LGBM_DatasetSetFeatureNames(h, arr, 5), lib)
    bufs = [ctypes.create_string_buffer(64) for _ in range(5)]
    out_strs = (ctypes.c_char_p * 5)(*[ctypes.addressof(b) for b in bufs])
    n_names = ctypes.c_int()
    need = ctypes.c_size_t()
    _check(lib.LGBM_DatasetGetFeatureNames(
        h, 5, ctypes.byref(n_names), 64, ctypes.byref(need),
        ctypes.cast(out_strs, ctypes.POINTER(ctypes.c_char_p))), lib)
    assert n_names.value == 5
    assert [b.value for b in bufs] == names
    assert need.value == len(b"gamma") + 1

    # clear group (zero-length clears, like the reference) so the binary
    # objective trains; booster-side names flow from the dataset
    _check(lib.LGBM_DatasetSetField(h, b"group", None, 0, 2), lib)
    bh = _train(lib, h)
    _check(lib.LGBM_BoosterGetFeatureNames(
        bh, 5, ctypes.byref(n_names), 64, ctypes.byref(need),
        ctypes.cast(out_strs, ctypes.POINTER(ctypes.c_char_p))), lib)
    assert [b.value for b in bufs] == names

    # validate-feature-names: match ok, mismatch errors
    _check(lib.LGBM_BoosterValidateFeatureNames(bh, arr, 5), lib)
    bad = (ctypes.c_char_p * 5)(b"a", b"b", b"c", b"d", b"e")
    assert lib.LGBM_BoosterValidateFeatureNames(bh, bad, 5) == -1
    assert b"Expected feature names" in lib.LGBM_GetLastError()

    n_eval = ctypes.c_int()
    _check(lib.LGBM_BoosterGetEvalNames(
        bh, 5, ctypes.byref(n_eval), 64, ctypes.byref(need),
        ctypes.cast(out_strs, ctypes.POINTER(ctypes.c_char_p))), lib)
    assert n_eval.value >= 1
    assert bufs[0].value == b"binary_logloss"
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_DatasetFree(h)


def test_save_binary_dump_text_subset(lib, data, tmp_path):
    X, y = data
    h = _dense_handle(lib, X, y)
    binpath = str(tmp_path / "d.npz").encode()
    _check(lib.LGBM_DatasetSaveBinary(h, binpath), lib)
    assert os.path.getsize(binpath) > 0

    txtpath = str(tmp_path / "d.txt").encode()
    _check(lib.LGBM_DatasetDumpText(h, txtpath), lib)
    lines = open(txtpath).read().splitlines()
    assert len(lines) == 1 + X.shape[0]

    idx = np.arange(0, 400, 2, dtype=np.int32)
    sh = ctypes.c_void_p()
    _check(lib.LGBM_DatasetGetSubset(
        h, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(idx), b"",
        ctypes.byref(sh)), lib)
    n = ctypes.c_int32()
    _check(lib.LGBM_DatasetGetNumData(sh, ctypes.byref(n)), lib)
    assert n.value == 200
    lib.LGBM_DatasetFree(sh)
    lib.LGBM_DatasetFree(h)


def test_add_features_and_param_checking(lib, data):
    X, y = data
    h1 = _dense_handle(lib, X[:, :3], y)
    h2 = _dense_handle(lib, X[:, 3:], y)
    _check(lib.LGBM_DatasetAddFeaturesFrom(h1, h2), lib)
    nf = ctypes.c_int32()
    _check(lib.LGBM_DatasetGetNumFeature(h1, ctypes.byref(nf)), lib)
    assert nf.value == 5
    lib.LGBM_DatasetFree(h1)
    lib.LGBM_DatasetFree(h2)

    _check(lib.LGBM_DatasetUpdateParamChecking(
        b"max_bin=63 verbosity=-1", b"max_bin=63 learning_rate=0.2"), lib)
    assert lib.LGBM_DatasetUpdateParamChecking(
        b"max_bin=63", b"max_bin=255") == -1
    assert b"max_bin" in lib.LGBM_GetLastError()


def test_push_rows_by_csr_streaming(lib, data):
    X, y = data
    ref = _dense_handle(lib, X, y)
    sh = ctypes.c_void_p()
    _check(lib.LGBM_DatasetCreateByReference(ref, len(y), ctypes.byref(sh)), lib)
    csr = sp.csr_matrix(X)
    for lo in range(0, 400, 100):
        blk = csr[lo:lo + 100]
        _check(lib.LGBM_DatasetPushRowsByCSR(
            sh, blk.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p), 2,
            blk.indices.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
            blk.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int64(len(blk.indptr)), ctypes.c_int64(blk.nnz),
            ctypes.c_int64(X.shape[1]), lo), lib)
    yc = y.astype(np.float32)
    _check(lib.LGBM_DatasetSetField(
        sh, b"label", yc.ctypes.data_as(ctypes.c_void_p), len(yc), 0), lib)
    bh = _train(lib, sh)
    bh_ref = _train(lib, ref)
    assert _model_string(lib, bh) == _model_string(lib, bh_ref)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_BoosterFree(bh_ref)
    lib.LGBM_DatasetFree(sh)
    lib.LGBM_DatasetFree(ref)


def test_sampled_column_schema(lib, data):
    X, y = data
    n, f = X.shape
    # full-sample: schema from the sample == schema from the data
    cols = [np.ascontiguousarray(X[:, c], np.float64) for c in range(f)]
    idxs = [np.arange(n, dtype=np.int32) for _ in range(f)]
    col_ptrs = (ctypes.POINTER(ctypes.c_double) * f)(
        *[c.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for c in cols])
    idx_ptrs = (ctypes.POINTER(ctypes.c_int) * f)(
        *[i.ctypes.data_as(ctypes.POINTER(ctypes.c_int)) for i in idxs])
    counts = (ctypes.c_int * f)(*([n] * f))
    h = ctypes.c_void_p()
    _check(lib.LGBM_DatasetCreateFromSampledColumn(
        col_ptrs, idx_ptrs, f, counts, n, n, ctypes.c_int64(n),
        b"max_bin=63", ctypes.byref(h)), lib)
    Xc = np.ascontiguousarray(X, np.float64)
    _check(lib.LGBM_DatasetPushRows(
        h, Xc.ctypes.data_as(ctypes.c_void_p), 1, n, f, 0), lib)
    yc = y.astype(np.float32)
    _check(lib.LGBM_DatasetSetField(
        h, b"label", yc.ctypes.data_as(ctypes.c_void_p), len(yc), 0), lib)
    bh = _train(lib, h)
    bh_ref = _train(lib, _dense_handle(lib, X, y))
    assert _model_string(lib, bh) == _model_string(lib, bh_ref)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_BoosterFree(bh_ref)
    lib.LGBM_DatasetFree(h)


def test_streaming_with_metadata(lib, data):
    X, y = data
    ref = _dense_handle(lib, X, y)
    sh = ctypes.c_void_p()
    _check(lib.LGBM_DatasetCreateByReference(ref, len(y), ctypes.byref(sh)), lib)
    _check(lib.LGBM_DatasetInitStreaming(sh, 1, 0, 1, 1, 1, 1), lib)
    _check(lib.LGBM_DatasetSetWaitForManualFinish(sh, 1), lib)
    qid = np.repeat(np.arange(8), 50).astype(np.int32)
    for lo in range(0, 400, 100):
        blk = np.ascontiguousarray(X[lo:lo + 100], np.float64)
        lab = y[lo:lo + 100].astype(np.float32)
        w = np.full(100, 2.0, np.float32)
        q = qid[lo:lo + 100]
        _check(lib.LGBM_DatasetPushRowsWithMetadata(
            sh, blk.ctypes.data_as(ctypes.c_void_p), 1, 100, X.shape[1], lo,
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            None, q.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), 0), lib)
    _check(lib.LGBM_DatasetMarkFinished(sh), lib)
    bh = _train(lib, sh, params=b"objective=lambdarank num_leaves=7 verbosity=-1")
    it = ctypes.c_int()
    _check(lib.LGBM_BoosterGetCurrentIteration(bh, ctypes.byref(it)), lib)
    assert it.value == 3
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_DatasetFree(sh)
    lib.LGBM_DatasetFree(ref)


def test_serialized_reference_bytebuffer(lib, data):
    X, y = data
    ref = _dense_handle(lib, X, y)
    buf_h = ctypes.c_void_p()
    buf_len = ctypes.c_int32()
    _check(lib.LGBM_DatasetSerializeReferenceToBinary(
        ref, ctypes.byref(buf_h), ctypes.byref(buf_len)), lib)
    assert buf_len.value > 0
    raw = bytearray(buf_len.value)
    v = ctypes.c_uint8()
    for i in range(buf_len.value):
        _check(lib.LGBM_ByteBufferGetAt(buf_h, i, ctypes.byref(v)), lib)
        raw[i] = v.value
    assert lib.LGBM_ByteBufferGetAt(buf_h, buf_len.value, ctypes.byref(v)) == -1

    carr = (ctypes.c_uint8 * len(raw)).from_buffer(raw)
    h2 = ctypes.c_void_p()
    _check(lib.LGBM_DatasetCreateFromSerializedReference(
        carr, len(raw), ctypes.c_int64(len(y)), 1, b"", ctypes.byref(h2)), lib)
    Xc = np.ascontiguousarray(X, np.float64)
    _check(lib.LGBM_DatasetPushRows(
        h2, Xc.ctypes.data_as(ctypes.c_void_p), 1, len(y), X.shape[1], 0), lib)
    yc = y.astype(np.float32)
    _check(lib.LGBM_DatasetSetField(
        h2, b"label", yc.ctypes.data_as(ctypes.c_void_p), len(yc), 0), lib)
    bh = _train(lib, h2)
    bh_ref = _train(lib, ref)
    # schema round-tripped through bytes -> identical bins -> identical model
    assert _model_string(lib, bh) == _model_string(lib, bh_ref)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_BoosterFree(bh_ref)
    lib.LGBM_ByteBufferFree(buf_h)
    lib.LGBM_DatasetFree(h2)
    lib.LGBM_DatasetFree(ref)


def test_model_surgery(lib, data):
    X, y = data
    h = _dense_handle(lib, X, y)
    bh = _train(lib, h, iters=2)
    bh2 = _train(lib, h, iters=3)

    n_models = ctypes.c_int()
    _check(lib.LGBM_BoosterMerge(bh, bh2), lib)
    _check(lib.LGBM_BoosterNumberOfTotalModel(bh, ctypes.byref(n_models)), lib)
    assert n_models.value == 5

    k = ctypes.c_int()
    _check(lib.LGBM_BoosterNumModelPerIteration(bh, ctypes.byref(k)), lib)
    assert k.value == 1

    lin = ctypes.c_int()
    _check(lib.LGBM_BoosterGetLinear(bh, ctypes.byref(lin)), lib)
    assert lin.value == 0

    lo = ctypes.c_double()
    hi = ctypes.c_double()
    _check(lib.LGBM_BoosterGetLowerBoundValue(bh, ctypes.byref(lo)), lib)
    _check(lib.LGBM_BoosterGetUpperBoundValue(bh, ctypes.byref(hi)), lib)
    assert lo.value < hi.value

    val = ctypes.c_double()
    _check(lib.LGBM_BoosterGetLeafValue(bh, 0, 1, ctypes.byref(val)), lib)
    _check(lib.LGBM_BoosterSetLeafValue(
        bh, 0, 1, ctypes.c_double(val.value + 0.25)), lib)
    _check(lib.LGBM_BoosterGetLeafValue(bh, 0, 1, ctypes.byref(val2 := ctypes.c_double())), lib)
    assert abs(val2.value - (val.value + 0.25)) < 1e-12

    _check(lib.LGBM_BoosterShuffleModels(bh, 0, -1), lib)

    n64 = ctypes.c_int64()
    _check(lib.LGBM_BoosterCalcNumPredict(bh, 10, 0, 0, -1, ctypes.byref(n64)), lib)
    assert n64.value == 10
    _check(lib.LGBM_BoosterCalcNumPredict(bh, 10, 2, 0, -1, ctypes.byref(n64)), lib)
    assert n64.value == 50  # leaf-index: rows x 5 trees
    _check(lib.LGBM_BoosterCalcNumPredict(bh, 10, 3, 0, -1, ctypes.byref(n64)), lib)
    assert n64.value == 60  # contrib: rows x (features+1)

    # loaded params round-trip as JSON
    n = ctypes.c_int64()
    _check(lib.LGBM_BoosterGetLoadedParam(bh, ctypes.c_int64(0), ctypes.byref(n), None), lib)
    pbuf = ctypes.create_string_buffer(n.value)
    _check(lib.LGBM_BoosterGetLoadedParam(bh, ctypes.c_int64(n.value), ctypes.byref(n), pbuf), lib)
    import json

    params = json.loads(pbuf.value)
    assert params["num_leaves"] == 7

    lib.LGBM_BoosterFree(bh)
    lib.LGBM_BoosterFree(bh2)
    lib.LGBM_DatasetFree(h)


def test_refit_and_get_predict(lib, data):
    X, y = data
    h = _dense_handle(lib, X, y)
    bh = _train(lib, h, iters=3)

    n64 = ctypes.c_int64()
    _check(lib.LGBM_BoosterGetNumPredict(bh, 0, ctypes.byref(n64)), lib)
    assert n64.value == len(y)
    scores = np.zeros(len(y))
    _check(lib.LGBM_BoosterGetPredict(
        bh, 0, ctypes.byref(n64),
        scores.ctypes.data_as(ctypes.POINTER(ctypes.c_double))), lib)
    assert np.isfinite(scores).all() and scores.std() > 0

    # refit with the model's own leaf assignments on the SAME data is
    # (approximately) a fixed point: gradients are recomputed at the
    # model's init score exactly as training did (advisor r3 fix)
    pred_before = _predict_dense(lib, bh, X)
    nt = ctypes.c_int()
    _check(lib.LGBM_BoosterNumberOfTotalModel(bh, ctypes.byref(nt)), lib)
    leaf = np.zeros((len(y), nt.value), np.int32)
    out = np.zeros(len(y) * nt.value)
    _check(lib.LGBM_BoosterPredictForMat(
        bh, np.ascontiguousarray(X).ctypes.data_as(ctypes.c_void_p), 1,
        X.shape[0], X.shape[1], 1, 2, 0, -1, b"",
        ctypes.byref(n64), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))),
        lib)
    leaf[:] = out.reshape(len(y), nt.value).astype(np.int32)
    _check(lib.LGBM_BoosterRefit(
        bh, leaf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(y),
        nt.value), lib)
    pred_after = _predict_dense(lib, bh, X)
    assert np.isfinite(pred_after).all()
    np.testing.assert_allclose(pred_after, pred_before, rtol=1e-3, atol=1e-5)

    # flipped labels -> different gradients -> refit must move predictions
    yf = (1.0 - y).astype(np.float32)
    _check(lib.LGBM_DatasetSetField(
        h, b"label", yf.ctypes.data_as(ctypes.c_void_p), len(yf), 0), lib)
    _check(lib.LGBM_BoosterRefit(
        bh, leaf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(y),
        nt.value), lib)
    pred_flipped = _predict_dense(lib, bh, X)
    assert not np.allclose(pred_flipped, pred_after)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_DatasetFree(h)


def _predict_dense(lib, bh, X):
    out = np.zeros(X.shape[0])
    n = ctypes.c_int64()
    _check(lib.LGBM_BoosterPredictForMat(
        bh, np.ascontiguousarray(X).ctypes.data_as(ctypes.c_void_p), 1,
        X.shape[0], X.shape[1], 1, 0, 0, -1, b"",
        ctypes.byref(n), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))),
        lib)
    return out


def test_predict_for_file(lib, data, tmp_path):
    X, y = data
    h = _dense_handle(lib, X, y)
    bh = _train(lib, h)
    datafile = tmp_path / "rows.csv"
    np.savetxt(datafile, np.column_stack([y, X]), delimiter=",")
    result = tmp_path / "preds.txt"
    _check(lib.LGBM_BoosterPredictForFile(
        bh, str(datafile).encode(), 0, 0, 0, -1, b"", str(result).encode()),
        lib)
    got = np.loadtxt(result)
    np.testing.assert_allclose(got, _predict_dense(lib, bh, X), rtol=1e-9)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_DatasetFree(h)


def test_csr_single_row_and_fast(lib, data):
    X, y = data
    h = _dense_handle(lib, X, y)
    bh = _train(lib, h)
    expect = _predict_dense(lib, bh, X[:1])

    row = sp.csr_matrix(X[:1])
    out = np.zeros(1)
    n = ctypes.c_int64()
    _check(lib.LGBM_BoosterPredictForCSRSingleRow(
        bh, row.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p), 2,
        row.indices.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
        row.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(row.indptr)), ctypes.c_int64(row.nnz),
        ctypes.c_int64(X.shape[1]), 0, 0, -1, b"", ctypes.byref(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))), lib)
    np.testing.assert_allclose(out, expect, rtol=1e-12)

    fc = ctypes.c_void_p()
    _check(lib.LGBM_BoosterPredictForCSRSingleRowFastInit(
        bh, 0, 0, -1, 1, ctypes.c_int64(X.shape[1]), b"",
        ctypes.byref(fc)), lib)
    out2 = np.zeros(1)
    _check(lib.LGBM_BoosterPredictForCSRSingleRowFast(
        fc, row.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p), 2,
        row.indices.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
        row.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(row.indptr)), ctypes.c_int64(row.nnz),
        ctypes.byref(n),
        out2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))), lib)
    np.testing.assert_allclose(out2, expect, rtol=1e-12)
    lib.LGBM_FastConfigFree(fc)
    lib.LGBM_BoosterFree(bh)
    lib.LGBM_DatasetFree(h)


def test_global_config_entries(lib):
    # DumpParamAliases: valid JSON mapping canonical -> aliases
    n = ctypes.c_int64()
    _check(lib.LGBM_DumpParamAliases(ctypes.c_int64(0), ctypes.byref(n), None), lib)
    buf = ctypes.create_string_buffer(n.value)
    _check(lib.LGBM_DumpParamAliases(ctypes.c_int64(n.value), ctypes.byref(n), buf), lib)
    import json

    aliases = json.loads(buf.value)
    assert "num_threads" in aliases and "nthread" in aliases["num_threads"]

    nt = ctypes.c_int()
    _check(lib.LGBM_GetMaxThreads(ctypes.byref(nt)), lib)
    assert nt.value == -1
    _check(lib.LGBM_SetMaxThreads(4), lib)
    _check(lib.LGBM_GetMaxThreads(ctypes.byref(nt)), lib)
    assert nt.value == 4
    _check(lib.LGBM_SetMaxThreads(-1), lib)

    cnt = ctypes.c_int()
    _check(lib.LGBM_GetSampleCount(1000, b"bin_construct_sample_cnt=200", ctypes.byref(cnt)), lib)
    assert cnt.value == 200
    idx = np.zeros(200, np.int32)
    got = ctypes.c_int32()
    _check(lib.LGBM_SampleIndices(
        1000, b"bin_construct_sample_cnt=200",
        idx.ctypes.data_as(ctypes.c_void_p), ctypes.byref(got)), lib)
    assert got.value == 200
    assert (np.diff(idx) > 0).all() and idx.max() < 1000

    # log callback receives warning lines (earlier tests may have trained
    # with verbosity=-1, which sets the process-global level like the
    # reference's Log::ResetLogLevel — raise it so warnings emit, and
    # restore afterwards so later tests keep their expected quiet logs)
    from lightgbm_tpu.utils import log as _log

    prev_verbosity = _log._verbosity
    set_verbosity = _log.set_verbosity
    set_verbosity(1)
    seen = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p)
    cb = CB(lambda msg: seen.append(msg))
    _check(lib.LGBM_RegisterLogCallback(cb), lib)

    # network: single machine is a no-op bring-up; WithFunctions warns
    _check(lib.LGBM_NetworkInit(b"127.0.0.1:12400", 12400, 120, 1), lib)
    _check(lib.LGBM_NetworkFree(), lib)
    _check(lib.LGBM_NetworkInitWithFunctions(2, 0, None, None), lib)
    assert any(b"XLA collectives" in m for m in seen)
    _check(lib.LGBM_NetworkFree(), lib)
    # real collective fn pointers for a multi-machine run must FAIL without
    # the explicit opt-in (the host's transport cannot be silently swapped
    # for XLA's)
    fake_fn = ctypes.c_void_p(1)
    assert lib.LGBM_NetworkInitWithFunctions(2, 0, fake_fn, fake_fn) == -1
    assert b"ACCEPT_XLA_TRANSPORT" in lib.LGBM_GetLastError()
    set_verbosity(prev_verbosity)


def test_reset_training_data(lib):
    """LGBM_BoosterResetTrainingData: trees kept, later updates train on
    the new data (reference: GBDT::ResetTrainingData)."""
    rng = np.random.RandomState(31)
    X1 = rng.randn(400, 4)
    y1 = (X1 @ rng.randn(4) > 0).astype(np.float64)
    h1 = _dense_handle(lib, X1, y1)
    bh = _train(lib, h1, iters=2)
    X2 = rng.randn(300, 4)
    y2 = (X2 @ rng.randn(4) > 0).astype(np.float64)
    h2 = _dense_handle(lib, X2, y2)
    _check(lib.LGBM_BoosterResetTrainingData(bh, h2), lib)
    fin = ctypes.c_int()
    _check(lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)), lib)
    it = ctypes.c_int()
    _check(lib.LGBM_BoosterGetCurrentIteration(bh, ctypes.byref(it)), lib)
    assert it.value == 3  # two original iterations + one on the new data
    # model still predicts finite values on both datasets
    out = np.zeros(5, np.float64)
    out_len = ctypes.c_int64()
    Xc = np.ascontiguousarray(X2[:5], np.float64)
    _check(lib.LGBM_BoosterPredictForMat(
        bh, Xc.ctypes.data_as(ctypes.c_void_p), 1, 5, 4, 1, 0, 0, -1, b"",
        ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))), lib)
    assert np.isfinite(out).all()


def test_predict_sparse_output_contrib(lib):
    """LGBM_BoosterPredictSparseOutput: CSR SHAP output matches the dense
    pred_contrib path; FreePredictSparse releases the buffers."""
    rng = np.random.RandomState(32)
    X = rng.randn(300, 5)
    y = (X @ rng.randn(5) > 0).astype(np.float64)
    h = _dense_handle(lib, X, y)
    bh = _train(lib, h, iters=3)

    Xs = sp.csr_matrix(X)
    indptr = np.ascontiguousarray(Xs.indptr, np.int32)
    indices = np.ascontiguousarray(Xs.indices, np.int32)
    data = np.ascontiguousarray(Xs.data, np.float64)
    out_len = (ctypes.c_int64 * 2)()
    o_indptr = ctypes.c_void_p()
    o_indices = ctypes.POINTER(ctypes.c_int32)()
    o_data = ctypes.c_void_p()
    _check(lib.LGBM_BoosterPredictSparseOutput(
        bh, indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(X.shape[1]),
        3,  # C_API_PREDICT_CONTRIB
        0, -1, b"", 0,  # matrix_type CSR
        out_len, ctypes.byref(o_indptr), ctypes.byref(o_indices),
        ctypes.byref(o_data)), lib)
    n_indptr, nnz = out_len[0], out_len[1]
    assert n_indptr == X.shape[0] + 1
    got_indptr = np.ctypeslib.as_array(
        ctypes.cast(o_indptr, ctypes.POINTER(ctypes.c_int32)), (n_indptr,))
    got_indices = np.ctypeslib.as_array(o_indices, (nnz,))
    got_data = np.ctypeslib.as_array(
        ctypes.cast(o_data, ctypes.POINTER(ctypes.c_double)), (nnz,))
    got = sp.csr_matrix((got_data.copy(), got_indices.copy(),
                         got_indptr.copy()),
                        shape=(X.shape[0], X.shape[1] + 1)).toarray()
    # dense reference via the Python surface
    bst = lgb.Booster(model_str=_model_string(lib, bh))
    expect = bst.predict(X, pred_contrib=True)
    np.testing.assert_allclose(got, expect, rtol=1e-6, atol=1e-8)
    _check(lib.LGBM_BoosterFreePredictSparse(o_indptr, o_indices, o_data,
                                             2, 1), lib)
    # non-contrib predict_type must be rejected (reference: same check)
    assert lib.LGBM_BoosterPredictSparseOutput(
        bh, indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(X.shape[1]), 0, 0, -1, b"", 0,
        out_len, ctypes.byref(o_indptr), ctypes.byref(o_indices),
        ctypes.byref(o_data)) == -1


def _model_string(lib, bh):
    need = ctypes.c_int64()
    buf = ctypes.create_string_buffer(1)
    lib.LGBM_BoosterSaveModelToString(bh, 0, -1, 0, 1, ctypes.byref(need),
                                      buf)
    buf = ctypes.create_string_buffer(need.value)
    _check(lib.LGBM_BoosterSaveModelToString(
        bh, 0, -1, 0, need.value, ctypes.byref(need), buf), lib)
    return buf.value.decode()


def test_dataset_create_from_csr_func(lib, tmp_path):
    """LGBM_DatasetCreateFromCSRFunc: the reference's C++-ABI row-callback
    constructor.  A std::function cannot be built from Python, so a tiny
    C++ driver (compiled here, the ABI contract under test) wraps a
    callback and compares the resulting dataset against the mat path."""
    import subprocess
    import sysconfig

    src = tmp_path / "csrfunc_driver.cpp"
    so = tmp_path / "csrfunc_driver.so"
    src.write_text(r'''
#include <functional>
#include <utility>
#include <vector>
extern "C" int LGBM_DatasetCreateFromCSRFunc(void*, int, long long,
    const char*, void*, void**);
extern "C" int LGBM_DatasetGetNumData(void*, int*);
extern "C" int LGBM_DatasetGetNumFeature(void*, int*);
using RowFn = std::function<void(int, std::vector<std::pair<int,double>>&)>;
extern "C" int drive(int num_rows, long long num_col, int* out_rows,
                     int* out_cols) {
  RowFn fn = [num_col](int i, std::vector<std::pair<int,double>>& row) {
    for (int j = 0; j < num_col; ++j)
      if ((i + j) % 3 == 0) row.emplace_back(j, 0.25 * i + j);
  };
  void* ds = nullptr;
  int rc = LGBM_DatasetCreateFromCSRFunc(&fn, num_rows, num_col,
                                         "max_bin=15", nullptr, &ds);
  if (rc != 0) return rc;
  if (LGBM_DatasetGetNumData(ds, out_rows) != 0) return -2;
  if (LGBM_DatasetGetNumFeature(ds, out_cols) != 0) return -3;
  return 0;
}
''')
    from test_c_api import _SO
    subprocess.run(
        ["g++", "-O1", "-shared", "-fPIC", "-std=c++17", str(src),
         "-o", str(so), _SO, f"-Wl,-rpath,{os.path.dirname(_SO)}"],
        check=True, capture_output=True, text=True)
    drv = ctypes.CDLL(str(so))
    rows, cols = ctypes.c_int(), ctypes.c_int()
    rc = drv.drive(60, 7, ctypes.byref(rows), ctypes.byref(cols))
    assert rc == 0, lib.LGBM_GetLastError()
    assert rows.value == 60 and cols.value == 7


def test_dataset_get_feature_num_bin(lib):
    rng = np.random.RandomState(33)
    X = rng.randn(500, 3)
    y = (X[:, 0] > 0).astype(np.float64)
    h = _dense_handle(lib, X, y, params=b"max_bin=15")
    _train(lib, h, iters=1)  # forces construction
    nb = ctypes.c_int()
    _check(lib.LGBM_DatasetGetFeatureNumBin(h, 0, ctypes.byref(nb)), lib)
    assert 2 <= nb.value <= 16
    assert lib.LGBM_DatasetGetFeatureNumBin(h, 99, ctypes.byref(nb)) == -1


def test_predict_sparse_output_contrib_f32(lib):
    """Round-7 parity fix: LGBM_BoosterPredictSparseOutput honors the
    requested data_type — an f32 request gets f32 output buffers (the
    reference allocates per data_type; this surface was f64-only)."""
    rng = np.random.RandomState(33)
    X = rng.randn(250, 4)
    y = (X @ rng.randn(4) > 0).astype(np.float64)
    h = _dense_handle(lib, X, y)
    bh = _train(lib, h, iters=3)

    Xs = sp.csr_matrix(np.asarray(X, np.float32))
    indptr = np.ascontiguousarray(Xs.indptr, np.int32)
    indices = np.ascontiguousarray(Xs.indices, np.int32)
    data = np.ascontiguousarray(Xs.data, np.float32)
    out_len = (ctypes.c_int64 * 2)()
    o_indptr = ctypes.c_void_p()
    o_indices = ctypes.POINTER(ctypes.c_int32)()
    o_data = ctypes.c_void_p()
    _check(lib.LGBM_BoosterPredictSparseOutput(
        bh, indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), 0,  # C_API_DTYPE_FLOAT32
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(X.shape[1]),
        3,  # C_API_PREDICT_CONTRIB
        0, -1, b"", 0,  # matrix_type CSR
        out_len, ctypes.byref(o_indptr), ctypes.byref(o_indices),
        ctypes.byref(o_data)), lib)
    n_indptr, nnz = out_len[0], out_len[1]
    assert n_indptr == X.shape[0] + 1
    got_indptr = np.ctypeslib.as_array(
        ctypes.cast(o_indptr, ctypes.POINTER(ctypes.c_int32)), (n_indptr,))
    got_indices = np.ctypeslib.as_array(o_indices, (nnz,))
    # the data buffer is FLOAT32-typed — reading it as f32 must reproduce
    # the dense contrib path within f32 rounding
    got_data = np.ctypeslib.as_array(
        ctypes.cast(o_data, ctypes.POINTER(ctypes.c_float)), (nnz,))
    got = sp.csr_matrix((got_data.astype(np.float64), got_indices.copy(),
                         got_indptr.copy()),
                        shape=(X.shape[0], X.shape[1] + 1)).toarray()
    bst = lgb.Booster(model_str=_model_string(lib, bh))
    expect = bst.predict(X, pred_contrib=True)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)
    _check(lib.LGBM_BoosterFreePredictSparse(o_indptr, o_indices, o_data,
                                             2, 0), lib)
    # an integer data_type is still rejected
    assert lib.LGBM_BoosterPredictSparseOutput(
        bh, indptr.ctypes.data_as(ctypes.c_void_p), 2,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), 2,  # C_API_DTYPE_INT32
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(X.shape[1]), 3, 0, -1, b"", 0,
        out_len, ctypes.byref(o_indptr), ctypes.byref(o_indices),
        ctypes.byref(o_data)) == -1
