"""Serving-side compile/dispatch budget pins (round 9, ISSUE 4).

The training loop got its executable budget in rounds 6-7
(tests/test_retrace.py); this suite pins the PREDICT side: a warm
``Booster.predict`` is one packed-cache hit (zero host re-pack), exactly
ONE device dispatch and ONE blocking pull — for single-class, multiclass
and the early-stop chunk loop — and the row-bucket ladder keeps the
traversal at one compile per bucket across arbitrary batch sizes.
Padded-vs-unpadded and one-dispatch-vs-per-class outputs are pinned
BIT-identical, so the serving layer can never drift from the reference
predict semantics silently.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import _predict_bucket
from lightgbm_tpu.ops import predict as predict_ops
from lightgbm_tpu.utils.sanitizer import CompileCounter, DispatchCounter


def _binary_booster(n=600, f=6, rounds=5, seed=0, **extra):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    d = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    params.update(extra)
    bst = lgb.Booster(params=params, train_set=d)
    for _ in range(rounds):
        bst.update()
    return bst, X, y


def _multiclass_booster(n=500, f=5, k=3, rounds=4, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = rng.randint(0, k, n).astype(float)
    d = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "multiclass", "num_class": k,
                              "num_leaves": 7, "verbosity": -1}, train_set=d)
    for _ in range(rounds):
        bst.update()
    return bst, X


def test_bucket_ladder_shape():
    assert _predict_bucket(1) == 8
    assert _predict_bucket(7) == 8
    assert _predict_bucket(8) == 8
    assert _predict_bucket(128) == 128
    assert _predict_bucket(129) == 256
    assert _predict_bucket(4000) == 4096


def test_warm_predict_is_one_dispatch_one_sync_zero_repack():
    """The steady-state serving contract: packed cache hit (no _stacked
    call), 1 dispatch, 1 blocking pull, 0 traces/compiles."""
    bst, X, _ = _binary_booster()
    bst.predict(X, raw_score=True)  # warm: packs + compiles the bucket

    g = bst._gbdt
    packs = []
    orig = g._stacked

    def counting_stacked(*a, **kw):
        packs.append(1)
        return orig(*a, **kw)

    g._stacked = counting_stacked
    try:
        with DispatchCounter() as d:
            bst.predict(X, raw_score=True)
    finally:
        g._stacked = orig
    assert not packs, "warm predict re-packed the ensemble host-side"
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm single-class predict_raw")


def test_bucket_ladder_compiles_once_per_bucket():
    """N in {1, 7, 128, 129, 4000} -> buckets {8, 8, 128, 256, 4096}: at
    most one compile per NEW bucket, zero on revisit (ISSUE acceptance)."""
    bst, _, _ = _binary_booster(n=4096)
    rng = np.random.RandomState(7)
    X = rng.randn(4000, 6)
    bst.predict(X[:1], raw_score=True)  # warm bucket 8

    with CompileCounter() as c:
        bst.predict(X[:1], raw_score=True)
        bst.predict(X[:7], raw_score=True)  # same bucket as N=1
    c.assert_no_recompile("N in {1,7} share the 8-bucket")

    for n in (128, 129, 4000):
        with CompileCounter() as cold:
            bst.predict(X[:n], raw_score=True)
        assert cold.compiles >= 1, f"N={n} should open a new bucket"
        with CompileCounter() as warm:
            bst.predict(X[:n], raw_score=True)
        warm.assert_no_recompile(f"bucket revisit at N={n}")


def test_padded_output_bit_identical_to_unpadded(monkeypatch):
    """Rows traverse independently: the bucket padding may NEVER change a
    result bit (the property that makes the ladder safe to default on)."""
    bst, X, _ = _binary_booster()
    padded = bst.predict(X[:129], raw_score=True)
    monkeypatch.setenv("LGBMTPU_PREDICT_BUCKETS", "0")
    unpadded = bst.predict(X[:129], raw_score=True)
    assert np.array_equal(padded, unpadded)

    bm, Xm = _multiclass_booster()
    monkeypatch.delenv("LGBMTPU_PREDICT_BUCKETS")
    p = bm.predict(Xm[:37], raw_score=True)
    monkeypatch.setenv("LGBMTPU_PREDICT_BUCKETS", "0")
    u = bm.predict(Xm[:37], raw_score=True)
    assert np.array_equal(p, u)


def test_multiclass_one_dispatch_and_bitwise_vs_per_class():
    """Multiclass raw prediction is ONE dispatch (the round-6 per-class
    host loop was k dispatches) and bit-identical to the per-class path."""
    bst, X = _multiclass_booster()
    k = bst.num_model_per_iteration()
    new = bst.predict(X, raw_score=True)  # warm + result

    with DispatchCounter() as d:
        again = bst.predict(X, raw_score=True)
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm multiclass predict_raw")
    assert np.array_equal(new, again)

    # the replaced implementation: one predict_raw_values per class slice
    g = bst._gbdt
    s = g._packed(0, -1)
    x = jnp.asarray(np.asarray(X, np.float32))
    parts = []
    for c in range(k):
        sel = slice(c, s["T"], k)
        parts.append(predict_ops.predict_raw_values(
            x, s["split_feature"][sel], s["threshold"][sel],
            s["default_left"][sel], s["missing_type"][sel],
            s["left_child"][sel], s["right_child"][sel],
            s["num_leaves"][sel], s["leaf_value"][sel]))
    old = np.asarray(jnp.stack(parts, axis=1), np.float64)
    assert np.array_equal(new, old), np.abs(new - old).max()


def test_early_stop_chunks_reuse_one_executable():
    """Prediction early-stopping keeps all rows in the padded batch and
    masks on device: warm chunks are 1 dispatch + 1 (real data dependency)
    pull each, and NOTHING recompiles across chunks or batch sizes within
    a bucket — the old X[active] path compiled per distinct active count."""
    bst, X, _ = _binary_booster(rounds=8, pred_early_stop=True,
                                pred_early_stop_freq=2,
                                pred_early_stop_margin=0.5)
    first = bst.predict(X)  # warm: compiles the chunk window once

    with DispatchCounter() as d:
        again = bst.predict(X)
    d.assert_no_recompile("warm early-stop chunks")
    assert np.array_equal(first, again)
    assert d.dispatches >= 1
    # the margin test after each chunk is the loop's exit condition: one
    # accounted blocking pull per chunk, nothing else
    assert d.host_syncs == d.dispatches, (d.dispatches, d.host_syncs)
    # a different batch size in the same bucket must stay warm too
    # (600 and 550 both pad to the 1024 bucket)
    with DispatchCounter() as d2:
        bst.predict(X[:550])
    d2.assert_no_recompile("early-stop at a second N in the same bucket")


def test_early_stop_matches_legacy_chunked_walk():
    """The masked-on-device rework is numerically identical to the
    shrinking-active-set implementation it replaced."""
    bst, X, _ = _binary_booster(rounds=8, pred_early_stop=True,
                                pred_early_stop_freq=2,
                                pred_early_stop_margin=0.5)
    g = bst._gbdt
    new = g._predict_raw_early_stop(X)

    k = g.num_tree_per_iteration
    total = len(g.models) // k
    freq = max(int(g.cfg.pred_early_stop_freq), 1)
    margin = float(g.cfg.pred_early_stop_margin)
    n = X.shape[0]
    raw = None
    active = np.ones(n, bool)
    it = 0
    while it < total:
        chunk = min(freq, total - it)
        if raw is None:
            raw = g.predict_raw(X, it, chunk)
        else:
            raw[active] += g.predict_raw(X[active], it, chunk)
        it += chunk
        active &= np.abs(raw) < margin
        if not active.any():
            break
    assert np.array_equal(new, raw)


def test_pred_leaf_device_traversal_matches_host_walk():
    """pred_leaf rides the stacked device traversal now — one dispatch,
    same leaf assignment as the per-tree host walk it replaced."""
    bst, X, _ = _binary_booster()
    leaves = bst.predict(X, pred_leaf=True)
    host = np.stack([t.predict_leaf(np.asarray(X, np.float64))
                     for t in bst._gbdt.models], axis=1)
    assert leaves.shape == host.shape
    assert np.array_equal(leaves, host)

    bst.predict(X, pred_leaf=True)  # warm
    with DispatchCounter() as d:
        bst.predict(X, pred_leaf=True)
    assert d.dispatches == 1
    assert d.host_syncs == 1
    d.assert_no_recompile("warm pred_leaf")


def test_converted_predict_is_one_dispatch_one_sync(monkeypatch):
    """Round 12: objective.convert_output is FUSED into the traversal
    dispatch — a converted warm predict is 1 dispatch + 1 accounted pull
    (it was 2 dispatches: traversal, then a separate convert), and the
    fused result is bitwise the legacy 2-dispatch path's."""
    bst, X, _ = _binary_booster()
    fused = bst.predict(X)  # warm: packs + compiles the fused bucket

    with DispatchCounter() as d:
        again = bst.predict(X)
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm converted predict")
    assert np.array_equal(fused, again)

    # the legacy 2-dispatch path must still exist (escape hatch) and be
    # bitwise identical
    monkeypatch.setenv("LGBMTPU_FUSED_CONVERT", "0")
    legacy_warm = bst.predict(X)  # warm the legacy convert executable
    with DispatchCounter() as d2:
        legacy = bst.predict(X)
    assert d2.dispatches == 2, d2.dispatches
    assert np.array_equal(fused, legacy) and np.array_equal(
        legacy_warm, legacy)


def test_converted_predict_multiclass_one_dispatch_and_bitwise(monkeypatch):
    bm, Xm = _multiclass_booster()
    fused = bm.predict(Xm)
    with DispatchCounter() as d:
        again = bm.predict(Xm)
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm converted multiclass predict")
    assert np.array_equal(fused, again)

    monkeypatch.setenv("LGBMTPU_FUSED_CONVERT", "0")
    legacy = bm.predict(Xm)
    assert np.array_equal(fused, legacy)


def test_converted_predict_bucket_padding_bit_identical(monkeypatch):
    """The fused convert rides the same bucket ladder: padding may never
    change a converted bit either."""
    bst, X, _ = _binary_booster()
    padded = bst.predict(X[:129])
    monkeypatch.setenv("LGBMTPU_PREDICT_BUCKETS", "0")
    unpadded = bst.predict(X[:129])
    assert np.array_equal(padded, unpadded)


# ---------------------------------------------------------------------------
# stale-cache hazard (ISSUE satellite): mutation after a predict must
# invalidate the packed ensemble
# ---------------------------------------------------------------------------

def test_training_after_predict_invalidates_packed_cache():
    bst, X, _ = _binary_booster(rounds=3)
    before = bst.predict(X, raw_score=True)
    for _ in range(3):
        bst.update()
    after = bst.predict(X, raw_score=True)
    assert not np.array_equal(before, after), \
        "predictions ignored the newly trained trees (stale packed cache)"
    # the fresh result must equal a fresh booster's view of the same model
    clone = lgb.Booster(model_str=bst.model_to_string())
    assert np.array_equal(after, clone.predict(X, raw_score=True))


def test_rollback_after_predict_invalidates_packed_cache():
    bst, X, _ = _binary_booster(rounds=4)
    four = bst.predict(X, raw_score=True)
    bst.rollback_one_iter()
    three = bst.predict(X, raw_score=True)
    assert not np.array_equal(four, three)
    clone = lgb.Booster(model_str=bst.model_to_string())
    assert np.array_equal(three, clone.predict(X, raw_score=True))


def test_refit_and_leaf_edit_invalidate_packed_cache():
    bst, X, y = _binary_booster(rounds=4)
    base = bst.predict(X, raw_score=True)

    refit = bst.refit(X, y, decay_rate=0.0)
    refit.predict(X, raw_score=True)  # populate ITS cache, then mutate:
    refit.set_leaf_output(0, 0, 123.0)
    edited = refit.predict(X, raw_score=True)
    clone = lgb.Booster(model_str=refit.model_to_string())
    assert np.array_equal(edited, clone.predict(X, raw_score=True))
    assert not np.array_equal(base, edited)


def test_shuffle_models_invalidates_packed_cache():
    """Order changes the early-stop chunking but not the full sum; the
    cache must repack either way.  Round 18: mutation BUMPS the pack
    version instead of nulling the dict — the pre-shuffle entries stay
    resident (hot-swap friendliness) but are unreachable by the new
    version-keyed lookup, so the next predict packs fresh."""
    bst, X, _ = _binary_booster(rounds=4)
    bst.predict(X, raw_score=True)
    g = bst._gbdt
    assert g._pred_cache  # populated
    v0 = g._pack_version
    np.random.seed(0)
    bst.shuffle_models()
    assert g._pack_version == v0 + 1
    assert all(key[0] <= v0 for key in g._pred_cache), \
        "shuffle left a current-version packed ensemble cached"
    from lightgbm_tpu.obs import metrics as _obs
    misses0 = _obs.counter("predict_packed_cache_misses_total").value
    bst.predict(X, raw_score=True)
    assert _obs.counter("predict_packed_cache_misses_total").value \
        == misses0 + 1, "post-shuffle predict served a stale pack"


def test_packed_versioning_keeps_previous_pack_servable_during_swap():
    """The hot-swap mechanism (round 18, lightgbm_tpu/serve + the
    continuous-training roadmap item): a mutation bumps the version, and
    the PREVIOUS version's pack stays resident and servable — an
    in-flight serving reader that grabbed the pre-mutation pack keeps
    working, bitwise, while new predicts see the new trees."""
    bst, X, _ = _binary_booster(rounds=3)
    old_clone = lgb.Booster(model_str=bst.model_to_string())
    before = bst.predict(X[:40], raw_score=True)
    g = bst._gbdt
    s_old = g._packed(0, -1)
    v0 = g._pack_version
    bst.update()  # the swap: in-place mutation under a live serving loop
    # the old pack is retained one version back...
    assert any(key[0] == v0 for key in g._pred_cache), \
        "mutation evicted the in-flight pack"
    # ...and its device arrays still serve the OLD model's bits
    nb = _predict_bucket(40)
    x = g._pad_rows(np.asarray(X[:40], np.float64), nb)
    active = g._active_mask(40, nb)
    out = predict_ops.predict_raw_values(
        x, s_old["split_feature"], s_old["threshold"],
        s_old["default_left"], s_old["missing_type"], s_old["left_child"],
        s_old["right_child"], s_old["num_leaves"], s_old["leaf_value"],
        active=active)
    got_old = np.asarray(out, np.float64)[:40]
    assert np.array_equal(got_old, before)
    assert np.array_equal(before, old_clone.predict(X[:40], raw_score=True))
    # new predicts use the new version (fresh trees included)
    after = bst.predict(X[:40], raw_score=True)
    assert not np.array_equal(before, after)


def test_stale_pack_versions_evicted_and_counted():
    """Retention is LRU-bounded (default: current + previous version);
    older versions evict with a counter, so a long-lived serving process
    training every round cannot leak packs."""
    from lightgbm_tpu.obs import metrics as _obs

    bst, X, _ = _binary_booster(rounds=2)
    g = bst._gbdt
    evict0 = _obs.counter("predict_stale_pack_evictions_total").value
    versions = []
    for _ in range(3):
        bst.predict(X[:16], raw_score=True)  # populate this version's pack
        versions.append(g._pack_version)
        bst.update()  # bump
    assert _obs.counter("predict_stale_pack_evictions_total").value \
        > evict0
    live = {key[0] for key in g._pred_cache}
    keep = g._PACKED_KEEP_VERSIONS
    assert all(v > g._pack_version - keep for v in live), (live,
                                                          g._pack_version)
    assert versions[0] not in live  # the oldest version is gone


def test_coalesced_batch_budget_and_parity():
    """The serving loop's dispatch entry (GBDT.predict_coalesced): one
    coalesced batch of K requests is ONE dispatch + ONE accounted sync,
    reusing the SAME executables as warm predict (zero retraces), and
    the packed rows slice back out bitwise equal to the individual
    calls.  The runtime-level version (threads + staging + server ON)
    lives in tests/test_serve.py; this is the entry-level pin."""
    import jax

    bst, X, _ = _binary_booster()
    g = bst._gbdt
    parts = [X[0:10], X[10:17], X[17:32]]  # 32 rows: exact rung fill
    want = [bst.predict(p, raw_score=True) for p in parts]
    batch = np.concatenate(parts, axis=0)
    x = jax.device_put(np.asarray(batch, np.float64).astype(np.float32))
    g.predict_coalesced(x, None, 32, convert=False)  # warm the 32 bucket

    with DispatchCounter() as d:
        out = g.predict_coalesced(x, None, 32, convert=False)
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm coalesced batch")
    off = 0
    for w in want:
        assert np.array_equal(w, out[off:off + len(w)]), \
            "coalesced rows diverged from the individual predict"
        off += len(w)


# ---------------------------------------------------------------------------
# giant-batch row-sharded predict (parallel round: the third mesh axis)
# ---------------------------------------------------------------------------


def test_row_sharded_predict_bitwise_and_one_dispatch():
    """``Booster.predict(..., mesh=)`` scores a row-sharded batch as ONE
    SPMD dispatch over the row axis: rows traverse independently and each
    rank keeps the single-device tree-sum order, so the sharded result is
    BITWISE the single-device one — and a warm call keeps the exact
    serving budget (packed-cache hit, 1 dispatch, 1 accounted pull, 0
    retraces) with the replicated tables resident on the mesh."""
    from lightgbm_tpu.parallel.mesh import make_mesh

    bst, X, _ = _binary_booster()
    mesh = make_mesh()
    want = bst.predict(X, raw_score=True)
    got = bst.predict(X, raw_score=True, mesh=mesh)  # warm the mesh entry
    assert np.array_equal(want, got)

    g = bst._gbdt
    packs = []
    orig = g._stacked

    def counting_stacked(*a, **kw):
        packs.append(1)
        return orig(*a, **kw)

    g._stacked = counting_stacked
    try:
        with DispatchCounter() as d:
            again = bst.predict(X, raw_score=True, mesh=mesh)
    finally:
        g._stacked = orig
    assert not packs, "warm sharded predict re-packed the ensemble"
    assert d.dispatches == 1, d.dispatches
    assert d.host_syncs == 1, d.host_syncs
    d.assert_no_recompile("warm row-sharded predict")
    assert np.array_equal(want, again)

    # converted output rides the same sharded raw traversal, bitwise
    assert np.array_equal(bst.predict(X), bst.predict(X, mesh=mesh))
    # the explicit entry point is the same path
    assert np.array_equal(want, bst.predict_sharded(X, mesh, raw_score=True))


def test_row_sharded_predict_multiclass_bitwise():
    from lightgbm_tpu.parallel.mesh import make_mesh

    bm, Xm = _multiclass_booster()
    mesh = make_mesh()
    want = bm.predict(Xm, raw_score=True)
    assert np.array_equal(want, bm.predict(Xm, raw_score=True, mesh=mesh))
    bm.predict(Xm, raw_score=True, mesh=mesh)
    with DispatchCounter() as d:
        bm.predict(Xm, raw_score=True, mesh=mesh)
    assert d.dispatches == 1 and d.host_syncs == 1, (d.dispatches,
                                                     d.host_syncs)
    d.assert_no_recompile("warm multiclass row-sharded predict")
    assert np.array_equal(bm.predict(Xm), bm.predict(Xm, mesh=mesh))


def test_row_sharded_predict_on_training_mesh_and_invalidates():
    """A 2-D (feature x row) TRAINING mesh serves directly — P(data)
    shards rows and replicates over the feature axis — and mutation
    invalidates the mesh-resident tables with the pack itself."""
    from lightgbm_tpu.parallel.mesh import make_mesh_2d

    bst, X, _ = _binary_booster()
    mesh = make_mesh_2d(4, 2)
    want = bst.predict(X, raw_score=True)
    assert np.array_equal(want, bst.predict(X, raw_score=True, mesh=mesh))
    bst.update()  # bump the pack version
    after = bst.predict(X, raw_score=True, mesh=mesh)
    assert not np.array_equal(want, after)
    assert np.array_equal(after, bst.predict(X, raw_score=True))


def test_no_trees_and_single_row_paths():
    """Degenerate serving shapes: empty model and N=1 both work."""
    rng = np.random.RandomState(3)
    X = rng.randn(50, 4)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.Booster(params={"objective": "binary", "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    p = bst.predict(X, raw_score=True)  # zero trees: init score only
    assert p.shape == (50,)
    bst.update()
    one = bst.predict(X[:1], raw_score=True)
    assert one.shape == (1,)
    assert np.array_equal(one[0], bst.predict(X, raw_score=True)[0])
