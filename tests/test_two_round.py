"""two_round streaming ingestion (VERDICT r2 item "out-of-core"): the file
is read twice — sample+count, then chunked binning — and the raw float
matrix is never materialized (reference: DatasetLoader::LoadFromFile with
two_round=true)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _write_csv(path, n=20000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = ((X @ rng.randn(f)) > 0).astype(np.float64)
    arr = np.c_[y, X]
    np.savetxt(path, arr, delimiter=",", fmt="%.6f")
    return X, y


def test_two_round_matches_in_memory(tmp_path, monkeypatch):
    p = str(tmp_path / "train.csv")
    _write_csv(p)
    # compare against the PARSED file values (the csv text truncates floats)
    arr = np.loadtxt(p, delimiter=",")
    X, y = arr[:, 1:], arr[:, 0]

    bst_mem = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 5)

    # the eager full-file loader must NOT be used in two_round mode
    import lightgbm_tpu.io.parser as parser
    monkeypatch.setattr(parser, "load_data_file",
                        lambda *a, **k: (_ for _ in ()).throw(AssertionError(
                            "two_round used the eager loader")))
    ds = lgb.Dataset(p, params={"two_round": True})
    bst_stream = lgb.train({"objective": "binary", "num_leaves": 15,
                            "verbosity": -1, "two_round": True}, ds, 5)
    # n < bin_construct_sample_cnt: both paths bin from ALL rows -> the
    # models must be identical
    assert bst_stream.model_to_string() == bst_mem.model_to_string()


def test_two_round_chunked_paths(tmp_path):
    """Multiple chunks + reservoir sampling path (sample_cnt < n)."""
    p = str(tmp_path / "train.csv")
    X, y = _write_csv(p, n=30000, f=5, seed=1)
    ds = lgb.Dataset(p, params={"two_round": True,
                                "bin_construct_sample_cnt": 5000})
    import lightgbm_tpu.io.parser as parser
    orig = parser._iter_chunks
    calls = []

    def spy(path, fmt, header, chunk_rows):
        calls.append(1)
        return orig(path, fmt, header, 4096)  # force many chunks

    parser._iter_chunks = spy
    try:
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1, "two_round": True}, ds, 5)
    finally:
        parser._iter_chunks = orig
    assert len(calls) == 2  # exactly two passes over the file
    pred = bst.predict(X)
    auc = _auc(pred, y)
    assert auc > 0.8


def _auc(s, y):
    order = np.argsort(s)
    r = np.empty(len(s)); r[order] = np.arange(len(s))
    pos = y > 0
    return (r[pos].mean() - (pos.sum() - 1) / 2) / max((~pos).sum(), 1)


def test_two_round_file_dataset_plain_load(tmp_path):
    """A path Dataset WITHOUT two_round uses the eager loader (parity with
    the reference's Dataset('file') support)."""
    p = str(tmp_path / "train.csv")
    X, y = _write_csv(p, n=5000, f=4, seed=2)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(p), 3)
    arr = np.loadtxt(p, delimiter=",")
    Xp, yp = arr[:, 1:], arr[:, 0]
    bst_mem = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(Xp, label=yp), 3)
    # file datasets name features by FILE column (CLI convention), so
    # compare the models through their predictions
    np.testing.assert_allclose(bst.predict(Xp), bst_mem.predict(Xp), rtol=1e-7)
